"""MeanAveragePrecision vs an independent per-cell-loop COCO evaluator
(reference ``tests/detection/test_map.py`` uses pycocotools as oracle;
that package is unavailable offline, so the oracle here is a from-scratch
plain-loop implementation of the same protocol, fuzzed against the
vectorized implementation)."""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import MeanAveragePrecision
from tests.helpers.testers import _wire_virtual_ddp

IOU_THRS = np.linspace(0.5, 0.95, 10)
REC_THRS = np.linspace(0.0, 1.0, 101)
AREA_RANGES = {
    "all": (0, int(1e10)),
    "small": (0, 32**2),
    "medium": (32**2, 96**2),
    "large": (96**2, int(1e10)),
}
MAX_DETS = [1, 10, 100]


def _iou(d, g):
    lt = np.maximum(d[:, None, :2], g[None, :, :2])
    rb = np.minimum(d[:, None, 2:], g[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    a_d = (d[:, 2] - d[:, 0]) * (d[:, 3] - d[:, 1])
    a_g = (g[:, 2] - g[:, 0]) * (g[:, 3] - g[:, 1])
    union = a_d[:, None] + a_g[None, :] - inter
    return np.where(union > 0, inter / np.where(union > 0, union, 1), 0.0)


def _oracle_eval_img(det, scores, gt, area_range, max_det):
    """Plain-loop per-image, per-class evaluation (thresholds x dets loops)."""
    if len(gt) == 0 and len(det) == 0:
        return None
    areas = (gt[:, 2] - gt[:, 0]) * (gt[:, 3] - gt[:, 1])
    ignore = (areas < area_range[0]) | (areas > area_range[1])
    gtind = np.argsort(ignore, kind="stable")
    gt, gt_ignore = gt[gtind], ignore[gtind]
    order = np.argsort(-scores, kind="stable")[:max_det]
    det, scores = det[order], scores[order]
    ious = _iou(det, gt)

    T, D, G = len(IOU_THRS), len(det), len(gt)
    dtm = np.zeros((T, D), bool)
    gtm = np.zeros((T, G), bool)
    dti = np.zeros((T, D), bool)
    for ti, thr in enumerate(IOU_THRS):
        for di in range(D):
            vals = ious[di] * ~(gtm[ti] | gt_ignore)
            if G == 0:
                continue
            m = int(vals.argmax())
            if vals[m] > thr:
                dtm[ti, di] = True
                gtm[ti, m] = True
                dti[ti, di] = gt_ignore[m]
    if D:
        det_areas = (det[:, 2] - det[:, 0]) * (det[:, 3] - det[:, 1])
        out = (det_areas < area_range[0]) | (det_areas > area_range[1])
        dti = dti | (~dtm & out[None, :])
    return dict(dtm=dtm, gtm=gtm, scores=scores, gti=gt_ignore, dti=dti)


def _oracle_map(preds, targets, class_metrics=False):
    """Full plain-loop COCO evaluation over a corpus of per-image dicts."""
    classes = sorted(
        set(np.concatenate([np.asarray(p["labels"]).reshape(-1) for p in preds] +
                           [np.asarray(t["labels"]).reshape(-1) for t in targets]).astype(int).tolist())
        if preds or targets else []
    )
    n_imgs = len(preds)
    K, A, M, T, R = len(classes), len(AREA_RANGES), len(MAX_DETS), len(IOU_THRS), len(REC_THRS)
    precision = -np.ones((T, R, K, A, M))
    recall = -np.ones((T, K, A, M))

    for ki, cls in enumerate(classes):
        for ai, area_range in enumerate(AREA_RANGES.values()):
            evals = []
            for i in range(n_imgs):
                d_lab = np.asarray(preds[i]["labels"]).reshape(-1)
                g_lab = np.asarray(targets[i]["labels"]).reshape(-1)
                d_m, g_m = d_lab == cls, g_lab == cls
                if not d_m.any() and not g_m.any():
                    evals.append(None)
                    continue
                det = np.asarray(preds[i]["boxes"], float).reshape(-1, 4)[d_m]
                sc = np.asarray(preds[i]["scores"], float).reshape(-1)[d_m]
                gt = np.asarray(targets[i]["boxes"], float).reshape(-1, 4)[g_m]
                evals.append(_oracle_eval_img(det, sc, gt, area_range, MAX_DETS[-1]))
            evals = [e for e in evals if e is not None]
            if not evals:
                continue
            for mi, max_det in enumerate(MAX_DETS):
                scores = np.concatenate([e["scores"][:max_det] for e in evals])
                inds = np.argsort(-scores, kind="mergesort")
                dtm = np.concatenate([e["dtm"][:, :max_det] for e in evals], 1)[:, inds]
                dti = np.concatenate([e["dti"][:, :max_det] for e in evals], 1)[:, inds]
                gti = np.concatenate([e["gti"] for e in evals])
                npig = int((~gti).sum())
                if npig == 0:
                    continue
                tps = np.cumsum(dtm & ~dti, 1, dtype=float)
                fps = np.cumsum(~dtm & ~dti, 1, dtype=float)
                for ti in range(T):
                    tp, fp = tps[ti], fps[ti]
                    nd = len(tp)
                    rc = tp / npig
                    pr = tp / (fp + tp + np.finfo(float).eps)
                    recall[ti, ki, ai, mi] = rc[-1] if nd else 0
                    # right-max envelope via the reference's iterative lift
                    pr = pr.copy()
                    while True:
                        diff = np.clip(np.concatenate([pr[1:] - pr[:-1], [0.0]]), 0, None)
                        if np.all(diff == 0):
                            break
                        pr += diff
                    idxs = np.searchsorted(rc, REC_THRS, side="left")
                    num = int(idxs.argmax()) if idxs.max() >= nd else R
                    row = np.zeros(R)
                    row[:num] = pr[idxs[:num]]
                    precision[ti, :, ki, ai, mi] = row

    def summ(arr, avg_prec, thr=None, area="all", max_det=100):
        ai = list(AREA_RANGES).index(area)
        mi = MAX_DETS.index(max_det)
        x = arr[..., ai, mi]
        if thr is not None:
            x = x[list(IOU_THRS).index(thr)]
        v = x[x > -1]
        return float(v.mean()) if v.size else -1.0

    out = {
        "map": summ(precision, True),
        "map_50": summ(precision, True, 0.5),
        "map_75": summ(precision, True, 0.75),
        "map_small": summ(precision, True, area="small"),
        "map_medium": summ(precision, True, area="medium"),
        "map_large": summ(precision, True, area="large"),
        "mar_1": summ(recall, False, max_det=1),
        "mar_10": summ(recall, False, max_det=10),
        "mar_100": summ(recall, False, max_det=100),
        "mar_small": summ(recall, False, area="small"),
        "mar_medium": summ(recall, False, area="medium"),
        "mar_large": summ(recall, False, area="large"),
    }
    if class_metrics:
        out["map_per_class"] = [
            summ(precision[:, :, k : k + 1], True) for k in range(K)
        ]
        out["mar_100_per_class"] = [summ(recall[:, k : k + 1], False) for k in range(K)]
    return out


def _rand_corpus(rng, n_imgs, n_classes=3, max_boxes=8):
    preds, targets = [], []
    for _ in range(n_imgs):
        n_d = int(rng.integers(0, max_boxes))
        n_g = int(rng.integers(0, max_boxes))
        def boxes(n):
            xy = rng.uniform(0, 80, size=(n, 2))
            wh = rng.uniform(2, 60, size=(n, 2))
            return np.concatenate([xy, xy + wh], 1).astype(np.float32)
        preds.append(dict(
            boxes=jnp.asarray(boxes(n_d)),
            scores=jnp.asarray(rng.uniform(0, 1, n_d).astype(np.float32)),
            labels=jnp.asarray(rng.integers(0, n_classes, n_d)),
        ))
        targets.append(dict(
            boxes=jnp.asarray(boxes(n_g)),
            labels=jnp.asarray(rng.integers(0, n_classes, n_g)),
        ))
    return preds, targets


def _compare(result, want, keys=None):
    for k in keys or want:
        got = result[k]
        np.testing.assert_allclose(
            np.asarray(got, dtype=float), np.asarray(want[k], dtype=float), atol=1e-6, err_msg=k
        )


def test_reference_doctest_example():
    preds = [dict(boxes=jnp.asarray([[258.0, 41.0, 606.0, 285.0]]), scores=jnp.asarray([0.536]), labels=jnp.asarray([0]))]
    target = [dict(boxes=jnp.asarray([[214.0, 41.0, 562.0, 285.0]]), labels=jnp.asarray([0]))]
    m = MeanAveragePrecision()
    m.update(preds, target)
    r = m.compute()
    np.testing.assert_allclose(float(r["map"]), 0.6, atol=1e-4)
    np.testing.assert_allclose(float(r["map_50"]), 1.0, atol=1e-6)
    np.testing.assert_allclose(float(r["map_75"]), 1.0, atol=1e-6)
    np.testing.assert_allclose(float(r["mar_100"]), 0.6, atol=1e-4)
    assert float(r["map_medium"]) == -1.0


def test_perfect_predictions():
    rng = np.random.default_rng(3)
    _, targets = _rand_corpus(rng, 4)
    preds = [
        dict(boxes=t["boxes"], scores=jnp.ones(t["boxes"].shape[0]), labels=t["labels"]) for t in targets
    ]
    m = MeanAveragePrecision()
    m.update(preds, targets)
    r = m.compute()
    np.testing.assert_allclose(float(r["map"]), 1.0, atol=1e-6)
    np.testing.assert_allclose(float(r["mar_100"]), 1.0, atol=1e-6)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzz_vs_loop_oracle(seed):
    rng = np.random.default_rng(seed)
    preds, targets = _rand_corpus(rng, 6)
    m = MeanAveragePrecision(class_metrics=True)
    m.update(preds, targets)
    result = m.compute()
    want = _oracle_map(preds, targets, class_metrics=True)
    _compare(result, want)


def test_multiple_updates_match_single():
    rng = np.random.default_rng(9)
    preds, targets = _rand_corpus(rng, 6)
    m1 = MeanAveragePrecision()
    m1.update(preds[:3], targets[:3])
    m1.update(preds[3:], targets[3:])
    m2 = MeanAveragePrecision()
    m2.update(preds, targets)
    r1, r2 = m1.compute(), m2.compute()
    for k in r2:
        np.testing.assert_allclose(np.asarray(r1[k]), np.asarray(r2[k]), atol=1e-8, err_msg=k)


def test_virtual_ddp_matches_global():
    rng = np.random.default_rng(17)
    preds, targets = _rand_corpus(rng, 6)
    ranks = [MeanAveragePrecision() for _ in range(2)]
    _wire_virtual_ddp(ranks)
    ranks[0].update(preds[:3], targets[:3])
    ranks[1].update(preds[3:], targets[3:])
    synced = ranks[0].compute()
    want = _oracle_map(preds, targets)
    _compare(synced, want)


@pytest.mark.parametrize("box_format", ["xywh", "cxcywh"])
def test_box_formats(box_format):
    xyxy = np.asarray([[10.0, 20.0, 50.0, 80.0]], dtype=np.float32)
    if box_format == "xywh":
        conv = np.asarray([[10.0, 20.0, 40.0, 60.0]], dtype=np.float32)
    else:
        conv = np.asarray([[30.0, 50.0, 40.0, 60.0]], dtype=np.float32)
    m_ref = MeanAveragePrecision()
    m_ref.update(
        [dict(boxes=jnp.asarray(xyxy), scores=jnp.asarray([0.9]), labels=jnp.asarray([0]))],
        [dict(boxes=jnp.asarray(xyxy), labels=jnp.asarray([0]))],
    )
    m_fmt = MeanAveragePrecision(box_format=box_format)
    m_fmt.update(
        [dict(boxes=jnp.asarray(conv), scores=jnp.asarray([0.9]), labels=jnp.asarray([0]))],
        [dict(boxes=jnp.asarray(conv), labels=jnp.asarray([0]))],
    )
    np.testing.assert_allclose(float(m_ref.compute()["map"]), float(m_fmt.compute()["map"]), atol=1e-6)


def test_empty_preds_and_gt():
    m = MeanAveragePrecision()
    m.update(
        [dict(boxes=jnp.zeros((0, 4)), scores=jnp.zeros(0), labels=jnp.zeros(0, dtype=jnp.int32))],
        [dict(boxes=jnp.asarray([[10.0, 10.0, 20.0, 20.0]]), labels=jnp.asarray([1]))],
    )
    r = m.compute()
    np.testing.assert_allclose(float(r["map"]), 0.0, atol=1e-6)

    m2 = MeanAveragePrecision()
    m2.update(
        [dict(boxes=jnp.asarray([[10.0, 10.0, 20.0, 20.0]]), scores=jnp.asarray([0.5]), labels=jnp.asarray([1]))],
        [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros(0, dtype=jnp.int32))],
    )
    r2 = m2.compute()
    # no positives anywhere -> everything stays -1
    assert float(r2["map"]) == -1.0


def test_invalid_inputs():
    with pytest.raises(ValueError, match="box_format"):
        MeanAveragePrecision(box_format="bad")
    with pytest.raises(ValueError, match="class_metrics"):
        MeanAveragePrecision(class_metrics="yes")
    m = MeanAveragePrecision()
    with pytest.raises(ValueError, match="same length"):
        m.update([], [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros(0))])
    with pytest.raises(ValueError, match="`scores`"):
        m.update([dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros(0))], [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros(0))])


def test_box_ops_match_host_twins():
    """jnp box_iou/box_area must stay consistent with the host-side numpy
    implementations used inside MeanAveragePrecision.compute."""
    from metrics_tpu.detection.mean_ap import _np_box_area, _np_box_iou
    from metrics_tpu.functional.detection import box_area, box_iou

    rng = np.random.default_rng(3)
    a = rng.uniform(0, 100, size=(7, 2))
    b = rng.uniform(0, 100, size=(5, 2))
    boxes_a = np.concatenate([a, a + rng.uniform(0, 50, size=(7, 2))], axis=1)
    boxes_b = np.concatenate([b, b + rng.uniform(0, 50, size=(5, 2))], axis=1)
    # include a degenerate zero-area box
    boxes_a[0, 2:] = boxes_a[0, :2]
    np.testing.assert_allclose(np.asarray(box_area(jnp.asarray(boxes_a))), _np_box_area(boxes_a), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(box_iou(jnp.asarray(boxes_a), jnp.asarray(boxes_b))),
        _np_box_iou(boxes_a, boxes_b),
        rtol=1e-5,
        atol=1e-7,
    )


def test_empty_rank_sync_dtypes():
    """A rank that never saw data must gather empty buffers with the same
    dtypes as populated ranks (int32 labels/img_idx, float32 boxes/scores)."""
    from metrics_tpu.detection.mean_ap import _cat_or_empty

    assert _cat_or_empty([], "det_labels").dtype == jnp.int32
    assert _cat_or_empty([], "det_img_idx").dtype == jnp.int32
    assert _cat_or_empty([], "det_scores").dtype == jnp.float32
    assert _cat_or_empty([], "det_boxes").shape == (0, 4)

    rng = np.random.default_rng(5)
    preds, targets = _rand_corpus(rng, 4)
    ranks = [MeanAveragePrecision() for _ in range(2)]
    _wire_virtual_ddp(ranks)
    ranks[0].update(preds, targets)  # rank 1 gets nothing
    synced = ranks[0].compute()
    want = _oracle_map(preds, targets)
    _compare(synced, want)


def test_empty_update_noop():
    """update([], []) must be a no-op (a rank can receive zero images)."""
    m = MeanAveragePrecision()
    m.update([], [])
    box = jnp.asarray([[10.0, 10.0, 50.0, 60.0]])
    m.update([dict(boxes=box, scores=jnp.asarray([0.9]), labels=jnp.asarray([0]))],
             [dict(boxes=box, labels=jnp.asarray([0]))])
    np.testing.assert_allclose(float(m.compute()["map"]), 1.0, atol=1e-6)


def test_crowded_cell_bucketing():
    """A single crowded (image, class) cell must not change results (it only
    changes the padding bucket it lands in)."""
    rng = np.random.default_rng(21)
    preds, targets = _rand_corpus(rng, 6)
    # one image with many same-class gts
    gxy = rng.uniform(0, 100, (40, 2))
    targets[0] = dict(boxes=jnp.asarray(np.concatenate([gxy, gxy + 20], 1), dtype=jnp.float32),
                      labels=jnp.zeros(40, dtype=jnp.int32))
    m = MeanAveragePrecision()
    m.update(preds, targets)
    want = _oracle_map(preds, targets)
    _compare(m.compute(), want)
