"""Golden pycocotools values for MeanAveragePrecision.

The reference pins its mAP against inline pycocotools numbers computed from a
subset of the official cocoapi fake-detections file
(``/root/reference/tests/detection/test_map.py:39-196``; fixtures = coco
image ids 42/73/74/133, goldens = the "Official pycocotools results" block).
Those fixtures and expected values are portable — this file ports them as an
independent oracle for ``metrics_tpu/detection/mean_ap.py``, breaking the
shared-author risk of the fuzz oracle in ``test_map.py``.

Tolerance: the reference itself compares at ``atol=1e-1``
(``test_map.py:212``) because torchmetrics' evaluator is not bit-identical
to pycocotools; this implementation matches the published 3-decimal goldens
to ``atol=1e-2`` on every scalar field and both per-class vectors.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import MeanAveragePrecision


def _d(boxes, scores, labels):
    return dict(
        boxes=jnp.asarray(np.asarray(boxes, np.float32).reshape(-1, 4)),
        scores=jnp.asarray(np.asarray(scores, np.float32)),
        labels=jnp.asarray(np.asarray(labels, np.int32)),
    )


def _g(boxes, labels):
    return dict(
        boxes=jnp.asarray(np.asarray(boxes, np.float32).reshape(-1, 4)),
        labels=jnp.asarray(np.asarray(labels, np.int32)),
    )


# coco image ids 42, 73, 74, 133 (reference test_map.py:26-100)
_PREDS = [
    _d([[258.15, 41.29, 606.41, 285.07]], [0.236], [4]),
    _d([[61.00, 22.75, 565.00, 632.42], [12.66, 3.32, 281.26, 275.23]], [0.318, 0.726], [3, 2]),
    _d(
        [
            [87.87, 276.25, 384.29, 379.43],
            [0.00, 3.66, 142.15, 316.06],
            [296.55, 93.96, 314.97, 152.79],
            [328.94, 97.05, 342.49, 122.98],
            [356.62, 95.47, 372.33, 147.55],
            [464.08, 105.09, 495.74, 146.99],
            [276.11, 103.84, 291.44, 150.72],
        ],
        [0.546, 0.3, 0.407, 0.611, 0.335, 0.805, 0.953],
        [4, 1, 0, 0, 0, 0, 0],
    ),
    _d([[0.00, 2.87, 601.00, 421.52]], [0.699], [5]),
]
_TARGET = [
    _g([[214.1500, 41.2900, 562.4100, 285.0700]], [4]),
    _g([[13.00, 22.75, 548.98, 632.42], [1.66, 3.32, 270.26, 275.23]], [2, 2]),
    _g(
        [
            [61.87, 276.25, 358.29, 379.43],
            [2.75, 3.66, 162.15, 316.06],
            [295.55, 93.96, 313.97, 152.79],
            [326.94, 97.05, 340.49, 122.98],
            [356.62, 95.47, 372.33, 147.55],
            [462.08, 105.09, 493.74, 146.99],
            [277.11, 103.84, 292.44, 150.72],
        ],
        [4, 1, 0, 0, 0, 0, 0],
    ),
    _g([[13.99, 2.87, 640.00, 421.52]], [5]),
]

# "Official pycocotools results calculated from a subset of
# https://github.com/cocodataset/cocoapi/tree/master/results"
# (reference test_map.py:142-196)
_GOLDEN_SCALARS = {
    "map": 0.706,
    "map_50": 0.901,
    "map_75": 0.846,
    "map_small": 0.689,
    "map_medium": 0.800,
    "map_large": 0.701,
    "mar_1": 0.592,
    "mar_10": 0.716,
    "mar_100": 0.716,
    "mar_small": 0.767,
    "mar_medium": 0.800,
    "mar_large": 0.700,
}
_GOLDEN_MAP_PER_CLASS = [0.725, 0.800, 0.454, -1.000, 0.650, 0.900]
_GOLDEN_MAR_100_PER_CLASS = [0.780, 0.800, 0.450, -1.000, 0.650, 0.900]

ATOL = 1e-2


@pytest.fixture(scope="module")
def golden_result():
    metric = MeanAveragePrecision(class_metrics=True)
    # two update calls of two images each, like the reference's batch split
    metric.update(_PREDS[:2], _TARGET[:2])
    metric.update(_PREDS[2:], _TARGET[2:])
    return {k: np.asarray(v) for k, v in metric.compute().items()}


@pytest.mark.parametrize("field", sorted(_GOLDEN_SCALARS))
def test_golden_scalar(golden_result, field):
    np.testing.assert_allclose(float(golden_result[field]), _GOLDEN_SCALARS[field], atol=ATOL)


def test_golden_map_per_class(golden_result):
    np.testing.assert_allclose(golden_result["map_per_class"], _GOLDEN_MAP_PER_CLASS, atol=ATOL)


def test_golden_mar_100_per_class(golden_result):
    np.testing.assert_allclose(golden_result["mar_100_per_class"], _GOLDEN_MAR_100_PER_CLASS, atol=ATOL)


def test_golden_single_update_equivalent(golden_result):
    """Batching split must not change the result (streaming invariance)."""
    metric = MeanAveragePrecision(class_metrics=True)
    metric.update(_PREDS, _TARGET)
    single = metric.compute()
    for k, v in golden_result.items():
        np.testing.assert_allclose(np.asarray(single[k]), v, atol=1e-6, err_msg=k)


def test_issue_943_degenerate_pair():
    """Second fixture from the reference (empty-GT image alongside a match)."""
    metric = MeanAveragePrecision()
    metric.update(
        [_d([[258.0, 41.0, 606.0, 285.0]], [0.536], [0])],
        [_g([[214.0, 41.0, 562.0, 285.0]], [0])],
    )
    metric.update(
        [_d([[258.0, 41.0, 606.0, 285.0]], [0.536], [0])],
        [dict(boxes=jnp.zeros((0, 4)), labels=jnp.zeros((0,), jnp.int32))],
    )
    res = metric.compute()
    # pycocotools: one matched detection at IoU .5+, one unmatched FP
    np.testing.assert_allclose(float(res["map"]), 0.6, atol=ATOL)
    np.testing.assert_allclose(float(res["map_50"]), 1.0, atol=ATOL)
    np.testing.assert_allclose(float(res["mar_1"]), 0.6, atol=ATOL)


def test_negative_labels():
    """Labels are arbitrary ints (the dict grouping of the reference accepts
    them); the encoded-key grouping must not collide or divide by zero."""
    metric = MeanAveragePrecision(class_metrics=True)
    metric.update(
        [_d([[258.0, 41.0, 606.0, 285.0], [10.0, 10.0, 50.0, 50.0]], [0.536, 0.9], [-1, 3])],
        [_g([[214.0, 41.0, 562.0, 285.0], [10.0, 10.0, 50.0, 50.0]], [-1, 3])],
    )
    res = metric.compute()
    np.testing.assert_allclose(float(res["map_50"]), 1.0, atol=ATOL)
    assert np.asarray(res["map_per_class"]).shape == (2,)  # classes -1 and 3 kept distinct
    np.testing.assert_allclose(float(np.asarray(res["map_per_class"])[1]), 1.0, atol=ATOL)
