"""Pairwise kernels vs sklearn oracles
(reference ``tests/pairwise/test_pairwise_distance.py``)."""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics.pairwise import (
    cosine_similarity as sk_cosine,
    euclidean_distances as sk_euclidean,
    linear_kernel as sk_linear,
    manhattan_distances as sk_manhattan,
)

from metrics_tpu.functional import (
    pairwise_cosine_similarity,
    pairwise_euclidean_distance,
    pairwise_linear_similarity,
    pairwise_manhattan_distance,
)

_rng = np.random.default_rng(17)
_x = jnp.asarray(_rng.random((10, 6)), dtype=jnp.float32)
_y = jnp.asarray(_rng.random((8, 6)), dtype=jnp.float32)

_kernels = [
    pytest.param(pairwise_cosine_similarity, sk_cosine, id="cosine"),
    pytest.param(pairwise_euclidean_distance, sk_euclidean, id="euclidean"),
    pytest.param(pairwise_linear_similarity, sk_linear, id="linear"),
    pytest.param(pairwise_manhattan_distance, sk_manhattan, id="manhattan"),
]


@pytest.mark.parametrize("metric_fn, sk_fn", _kernels)
@pytest.mark.parametrize("reduction", [None, "mean", "sum"])
def test_pairwise_xy(metric_fn, sk_fn, reduction):
    result = metric_fn(_x, _y, reduction=reduction)
    expected = sk_fn(np.asarray(_x), np.asarray(_y))
    if reduction == "mean":
        expected = expected.mean(-1)
    elif reduction == "sum":
        expected = expected.sum(-1)
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("metric_fn, sk_fn", _kernels)
def test_pairwise_x_only_zero_diagonal(metric_fn, sk_fn):
    result = metric_fn(_x)
    expected = sk_fn(np.asarray(_x), np.asarray(_x))
    np.fill_diagonal(expected, 0)
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("metric_fn, sk_fn", _kernels)
def test_pairwise_keep_diagonal(metric_fn, sk_fn):
    result = metric_fn(_x, zero_diagonal=False)
    expected = sk_fn(np.asarray(_x), np.asarray(_x))
    # the ||x||^2+||y||^2-2xy expansion leaves sqrt(eps) on the self-distance
    # diagonal in float32, so compare at a looser absolute tolerance
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-3, rtol=1e-4)


def test_pairwise_input_errors():
    with pytest.raises(ValueError, match="Expected argument `x`.*"):
        pairwise_cosine_similarity(jnp.ones(5))
    with pytest.raises(ValueError, match="Expected argument `y`.*"):
        pairwise_cosine_similarity(jnp.ones((5, 2)), jnp.ones((5, 3)))
    with pytest.raises(ValueError, match="Expected reduction.*"):
        pairwise_cosine_similarity(jnp.ones((5, 2)), reduction="bad")
