"""WindowedMetric / DecayedMetric semantics + make_stream_step parity +
checkpoint kill-and-resume (the windowed acceptance pin).
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection, obs
from metrics_tpu.steps import make_stream_step
from metrics_tpu.streaming import DecayedMetric, StreamingAUROC, WindowedMetric


@pytest.fixture(scope="module")
def stream():
    rng = np.random.default_rng(9)
    preds = rng.uniform(0, 1, 20_000).astype(np.float32)
    target = (rng.uniform(0, 1, 20_000) < 0.3 + 0.4 * preds).astype(np.int32)
    return preds, target


def _batches(stream, n, size=1_000):
    preds, target = stream
    for i in range(n):
        sl = slice(i * size, (i + 1) * size)
        yield jnp.asarray(preds[sl]), jnp.asarray(target[sl])


def test_window_expiry_semantics():
    """The window covers exactly the last `window * updates_per_slot`
    updates; older shards are expired, not merely down-weighted."""
    w = WindowedMetric(Accuracy(), window=2, updates_per_slot=1)
    w.update(jnp.asarray([1, 1, 1, 1]), jnp.asarray([1, 1, 1, 1]))
    assert float(w.compute()) == 1.0
    w.update(jnp.asarray([0, 0, 0, 0]), jnp.asarray([1, 1, 1, 1]))
    assert float(w.compute()) == 0.5  # both shards live
    w.update(jnp.asarray([0, 0, 0, 0]), jnp.asarray([1, 1, 1, 1]))
    assert float(w.compute()) == 0.0  # the all-correct shard expired


def test_window_equals_exact_sliding_window(stream):
    """Against a brute-force recompute over the trailing batches."""
    preds, target = stream
    k, ups = 3, 2
    w = WindowedMetric(Accuracy(), window=k, updates_per_slot=ups)
    hist = []
    for pb, tb in _batches(stream, 9):
        pb_lbl = (pb > 0.5).astype(jnp.int32)
        w.update(pb_lbl, tb)
        hist.append((pb_lbl, tb))
        # live shard has 1..ups updates; expired shards are whole
        n_live = ((len(hist) - 1) % ups) + 1
        span = (k - 1) * ups + n_live
        exact = Accuracy()
        for b in hist[-span:]:
            exact.update(*b)
        assert float(w.compute()) == pytest.approx(float(exact.compute()), abs=1e-6)


def test_manual_advance():
    w = WindowedMetric(Accuracy(), window=2, updates_per_slot=None)
    for _ in range(5):  # all into one shard until the caller says otherwise
        w.update(jnp.asarray([1, 1]), jnp.asarray([1, 1]))
    w.advance()
    w.update(jnp.asarray([0, 0]), jnp.asarray([1, 1]))
    assert float(w.compute()) == pytest.approx(10 / 12)
    w.advance()  # expires the 10-correct shard
    assert float(w.compute()) == 0.0


def test_windows_expired_counter():
    prev = obs.enable()
    obs.reset()
    try:
        w = WindowedMetric(Accuracy(), window=2, updates_per_slot=1)
        for _ in range(4):
            w.update(jnp.asarray([1, 1]), jnp.asarray([1, 1]))
        # rotations happen lazily at updates 2,3,4; slots previously
        # written are cleared on rotations 3 and 4
        assert obs.get_counter("stream.windows_expired", metric="Accuracy") == 2
    finally:
        obs.enable(prev)
        obs.reset()


def test_windowed_sketch_base(stream):
    """A sketch-state metric as the windowed base: expiry drops its counts."""
    preds, target = stream
    w = WindowedMetric(StreamingAUROC(num_bins=64), window=2, updates_per_slot=1)
    for pb, tb in _batches(stream, 3):
        w.update(pb, tb)
    exact = StreamingAUROC(num_bins=64)
    for pb, tb in list(_batches(stream, 3))[-2:]:
        exact.update(pb, tb)
    assert float(w.compute()) == float(exact.compute())


def test_windowed_rejects_buffer_states():
    from metrics_tpu import AUROC

    with pytest.raises(ValueError, match="combinable"):
        WindowedMetric(AUROC(), window=2)  # cat-list states cannot expire
    with pytest.raises(ValueError, match="combinable"):
        DecayedMetric(AUROC(sample_capacity=128), half_life=2.0)


def test_decayed_rejects_max_states():
    from metrics_tpu import MaxMetric

    with pytest.raises(ValueError, match="combinable"):
        DecayedMetric(MaxMetric(), half_life=2.0)  # a max cannot fade


def test_decayed_half_life_weighting():
    d = DecayedMetric(Accuracy(), half_life=1.0)
    d.update(jnp.asarray([0, 0, 0, 0]), jnp.asarray([1, 1, 1, 1]))
    d.update(jnp.asarray([1, 1, 1, 1]), jnp.asarray([1, 1, 1, 1]))
    # recent all-correct weighs 2x the all-wrong batch: 2/3
    assert float(d.compute()) == pytest.approx(2 / 3, abs=1e-6)
    assert d.effective_window == pytest.approx(2.0)


def test_decayed_equals_exact_ewma(stream):
    d = DecayedMetric(MeanSquaredError(), half_life=3.0)
    decay = d.decay
    num = den = 0.0
    for pb, tb in _batches(stream, 6):
        d.update(pb, tb)
        num = num * decay + float(jnp.sum((pb - tb) ** 2))
        den = den * decay + pb.shape[0]
        assert float(d.compute()) == pytest.approx(num / den, rel=1e-5)


def test_wrappers_ride_collections(stream):
    coll = MetricCollection(
        {
            "acc_w": WindowedMetric(Accuracy(), window=2, updates_per_slot=1),
            "acc_d": DecayedMetric(Accuracy(), half_life=2.0),
        }
    )
    for pb, tb in _batches(stream, 3):
        coll.update((pb > 0.5).astype(jnp.int32), tb)
    res = coll.compute()
    assert set(res) == {"acc_w", "acc_d"}


def test_forward_returns_batch_value(stream):
    w = WindowedMetric(Accuracy(), window=2, updates_per_slot=1)
    pb = jnp.asarray([1, 0, 1, 1])
    tb = jnp.asarray([1, 1, 1, 1])
    assert float(w(pb, tb)) == 0.75  # batch-local value
    d = DecayedMetric(Accuracy(), half_life=2.0)
    assert float(d(pb, tb)) == 0.75


@pytest.mark.parametrize("ups", [1, 2])
def test_stream_step_parity_windowed(stream, ups):
    """make_stream_step == the eager wrapper, step by step, incl. rotation
    boundaries (one launch folds AND emits the window value)."""
    eager = WindowedMetric(StreamingAUROC(num_bins=64), window=3, updates_per_slot=ups)
    init, step, compute = make_stream_step(
        WindowedMetric(StreamingAUROC(num_bins=64), window=3, updates_per_slot=ups)
    )
    state = init()
    for pb, tb in _batches(stream, 8):
        eager.update(pb, tb)
        state, value = step(state, pb, tb)
        assert float(value) == float(eager.compute())
        assert float(compute(jax.tree_util.tree_map(lambda x: x, state))) == float(eager.compute())


def test_stream_step_parity_decayed(stream):
    eager = DecayedMetric(Accuracy(num_classes=2, multiclass=True), half_life=4.0)
    init, step, compute = make_stream_step(
        DecayedMetric(Accuracy(num_classes=2, multiclass=True), half_life=4.0)
    )
    state = init()
    for pb, tb in _batches(stream, 5):
        pb_lbl = (pb > 0.5).astype(jnp.int32)
        eager.update(pb_lbl, tb)
        state, value = step(state, pb_lbl, tb)
        assert float(value) == pytest.approx(float(eager.compute()), rel=1e-6)


def test_stream_step_requires_wrapper():
    with pytest.raises(ValueError, match="WindowedMetric or DecayedMetric"):
        make_stream_step(Accuracy())
    with pytest.raises(ValueError, match="updates_per_slot"):
        make_stream_step(WindowedMetric(Accuracy(), window=2, updates_per_slot=None))


def test_stream_step_single_trace(stream):
    """The whole fold+rotate+compute pipeline is ONE jitted program: a
    second same-shape step call must not retrace."""
    prev = obs.enable()
    obs.reset()
    try:
        init, step, _ = make_stream_step(
            WindowedMetric(StreamingAUROC(num_bins=32), window=2, updates_per_slot=1)
        )
        state = init()
        batches = list(_batches(stream, 3))
        for pb, tb in batches:
            state, _ = step(state, pb, tb)
        label = "WindowedMetric[StreamingAUROC].stream_step"
        assert obs.get_counter("step.traces", step=label) == 1
    finally:
        obs.enable(prev)
        obs.reset()


def test_windowed_kill_resume_bitwise(tmp_path, stream):
    """ACCEPTANCE: kill-and-resume of a windowed metric through
    ft.CheckpointManager reproduces compute() bitwise — ring position,
    shard fill bookkeeping and sketch states all survive the manifest
    round-trip, and the journal watermark keeps the resume exactly-once."""
    from metrics_tpu.ft import BatchJournal, CheckpointManager

    preds, target = stream
    batches = list(_batches(stream, 6))

    # uninterrupted run
    uninterrupted = WindowedMetric(StreamingAUROC(num_bins=64), window=2, updates_per_slot=2)
    for epoch_step, (pb, tb) in enumerate(batches):
        uninterrupted.update(pb, tb)

    # "killed" after batch 2 (checkpoint saved), resumed in a fresh object
    mgr = CheckpointManager(os.path.join(tmp_path, "ck"))
    journal = BatchJournal()
    victim = WindowedMetric(StreamingAUROC(num_bins=64), window=2, updates_per_slot=2)
    for epoch_step, (pb, tb) in enumerate(batches[:3]):
        victim.update(pb, tb)
        journal.record(0, epoch_step)
    mgr.save(victim, journal=journal, epoch=0, step=2)
    del victim  # the kill

    resumed = WindowedMetric(StreamingAUROC(num_bins=64), window=2, updates_per_slot=2)
    j2 = BatchJournal()
    mgr.restore(resumed, journal=j2)
    for epoch_step, (pb, tb) in enumerate(batches):
        if not j2.should_fold(0, epoch_step):
            continue  # exactly-once: already in the restored state
        resumed.update(pb, tb)
        j2.record(0, epoch_step)

    assert resumed._pos == uninterrupted._pos
    assert resumed._slot_filled == uninterrupted._slot_filled
    assert float(resumed.compute()) == float(uninterrupted.compute())


def test_decayed_kill_resume_bitwise(tmp_path, stream):
    from metrics_tpu.ft import BatchJournal, CheckpointManager

    batches = list(_batches(stream, 4))
    uninterrupted = DecayedMetric(Accuracy(), half_life=2.0)
    for pb, tb in batches:
        uninterrupted.update((pb > 0.5).astype(jnp.int32), tb)

    mgr = CheckpointManager(os.path.join(tmp_path, "ck"))
    journal = BatchJournal()
    victim = DecayedMetric(Accuracy(), half_life=2.0)
    for step_i, (pb, tb) in enumerate(batches[:2]):
        victim.update((pb > 0.5).astype(jnp.int32), tb)
        journal.record(0, step_i)
    mgr.save(victim, journal=journal, epoch=0, step=1)

    resumed = DecayedMetric(Accuracy(), half_life=2.0)
    j2 = BatchJournal()
    mgr.restore(resumed, journal=j2)
    for step_i, (pb, tb) in enumerate(batches):
        if not j2.should_fold(0, step_i):
            continue
        resumed.update((pb > 0.5).astype(jnp.int32), tb)
        j2.record(0, step_i)
    assert float(resumed.compute()) == float(uninterrupted.compute())
