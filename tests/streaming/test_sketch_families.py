"""Sketch-family trio: merge algebra, 1M-sample oracles, platform seams.

Heavy hitters, distinct counts, and co-occurrence get the same two-part
contract the original sketches pinned:

1. merge is an exact monoid BITWISE — associative, commutative, fresh
   sketch as identity, invariant across shard counts and fold orders
   (HLL adds idempotence: re-merging the same payload is harmless);
2. estimates and ``error_bound()`` envelopes hold against exact NumPy
   references at 1M samples (top-k set exact within the overestimate
   envelope; HLL within the standard-error envelope; co-occurrence cell
   envelopes always contain the exact count).

Plus the jit/scan/vmap carry, pack-tree, history-delta, and windowed
seams every sketch state must ride.
"""
import collections
import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.streaming import (
    ChurnUndefinedError,
    CoOccurrenceSketch,
    DistinctCountSketch,
    HeavyHitterSketch,
    StreamingConfusion,
    StreamingDistinctCount,
    StreamingTopK,
    merge_all,
    sketch_from_pack_tree,
)
from metrics_tpu.streaming.hashing import (
    ROW_SEEDS,
    bit_planes,
    bucket_index,
    fmix32,
    leading_rho,
    pack_bits,
    register_index,
)

N_BIG = 1_000_000


def _leaves_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )


def _fresh(kind):
    if kind == "hh":
        return HeavyHitterSketch(capacity=64, depth=4, id_bits=16)
    if kind == "distinct":
        return DistinctCountSketch(precision=8)
    return CoOccurrenceSketch(num_rows=300, num_cols=300, capacity=64, depth=4)


def _fold(kind, sk, ids):
    if kind == "cooccur":
        return sk.fold(jnp.asarray(ids % 300), jnp.asarray((ids * 13) % 300))
    return sk.fold(jnp.asarray(ids))


def _shard_sketches(kind, ids, n_shards):
    # equal-length shards: every fold shares one shape, so the eager
    # scatter kernels compile once per (kind, n_shards) instead of once
    # per shard (uneven sizes are pinned by test_uneven_shard_merge)
    return [
        _fold(kind, _fresh(kind), chunk) for chunk in ids.reshape(n_shards, -1)
    ]


@pytest.fixture(scope="module")
def stream():
    rng = np.random.default_rng(11)
    return (rng.zipf(1.5, 4096) % 2000).astype(np.int32)


# ---------------------------------------------------------------------------
# hashing primitives
# ---------------------------------------------------------------------------


class TestHashing:
    def test_fmix32_matches_reference_vectors(self):
        """Pin the murmur3 finalizer against Python-computed references —
        any drift would silently re-bucket every persisted sketch."""
        xs = np.asarray([0, 1, 0xDEADBEEF, 0xFFFFFFFF], dtype=np.uint32)

        def ref(x):
            x &= 0xFFFFFFFF
            x ^= x >> 16
            x = (x * 0x85EBCA6B) & 0xFFFFFFFF
            x ^= x >> 13
            x = (x * 0xC2B2AE35) & 0xFFFFFFFF
            x ^= x >> 16
            return x

        got = np.asarray(fmix32(jnp.asarray(xs)))
        assert got.tolist() == [ref(int(x)) for x in xs]

    def test_row_seeds_frozen(self):
        """The seed table is persistent-state ABI: reordering or editing
        it re-buckets every existing sketch. Pin its head."""
        assert len(ROW_SEEDS) == 16
        assert ROW_SEEDS[0] == 0x92CA2F0E
        assert len(set(ROW_SEEDS)) == 16

    def test_bit_planes_pack_roundtrip(self):
        ids = jnp.asarray([0, 1, 5, 1023, 65535], dtype=jnp.uint32)
        assert np.array_equal(np.asarray(pack_bits(bit_planes(ids, 16))), np.asarray(ids))

    def test_bucket_index_range_and_determinism(self):
        ids = jnp.arange(1000, dtype=jnp.uint32)
        for row in (0, 3, 15):
            b = np.asarray(bucket_index(ids, row, 37))
            assert b.min() >= 0 and b.max() < 37
            assert np.array_equal(b, np.asarray(bucket_index(ids, row, 37)))
        with pytest.raises(ValueError, match="seed table"):
            bucket_index(ids, 16, 37)

    def test_hll_rho_and_index(self):
        # hash with top-p bits = index; tail of zeros gives max rho
        p = 8
        h = jnp.asarray([0x00000000, 0xFF000000, 0x00800000], dtype=jnp.uint32)
        idx = np.asarray(register_index(h, p))
        assert idx.tolist() == [0, 0xFF, 0]
        rho = np.asarray(leading_rho(h, p))
        # all-zero tail -> 32-p+1; 0x00800000 tail has leading 1 at its top bit -> rho 1
        assert rho.tolist() == [25, 25, 1]


# ---------------------------------------------------------------------------
# merge algebra: bitwise monoid across shard counts and fold orders
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", ["hh", "distinct", "cooccur"])
@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_merge_associative_commutative_bitwise(kind, n_shards, stream):
    """Every permutation and parenthesization of shard merges produces
    the SAME sketch, bitwise (ragged splits: test_uneven_shard_merge)."""
    pieces = _shard_sketches(kind, stream, n_shards)
    reference = merge_all(pieces)
    for perm in itertools.islice(itertools.permutations(range(n_shards)), 12):
        assert _leaves_equal(reference, merge_all([pieces[i] for i in perm]))
    level = list(pieces)
    while len(level) > 1:
        level = [
            level[i].merge(level[i + 1]) if i + 1 < len(level) else level[i]
            for i in range(0, len(level), 2)
        ]
    assert _leaves_equal(reference, level[0])


def test_uneven_shard_merge_bitwise(stream):
    """Uneven shard sizes change nothing: ragged splits merge to the
    same state as the flat fold, in either merge order."""
    flat = _fold("hh", _fresh("hh"), stream)
    pieces = [_fold("hh", _fresh("hh"), part) for part in (stream[:37], stream[37:])]
    assert _leaves_equal(flat, merge_all(pieces))
    assert _leaves_equal(flat, merge_all(list(reversed(pieces))))


@pytest.mark.parametrize("kind", ["hh", "distinct", "cooccur"])
def test_fresh_sketch_is_identity(kind, stream):
    folded = _fold(kind, _fresh(kind), stream)
    assert _leaves_equal(folded, folded.merge(_fresh(kind)))
    assert _leaves_equal(folded, _fresh(kind).merge(folded))


def test_hll_merge_idempotent(stream):
    """The distinct sketch's max-merge is idempotent — duplicate payload
    delivery (a retried wire ship) cannot inflate the estimate."""
    sk = DistinctCountSketch(precision=8).fold(jnp.asarray(stream))
    assert _leaves_equal(sk, sk.merge(sk))


@pytest.mark.parametrize("kind", ["hh", "distinct", "cooccur"])
def test_shard_count_invariance_bitwise(kind, stream):
    """2-way, 4-way, and 8-way sharded folds all merge to the same state
    as the single-shot fold — the serve tree's fan-in invariance."""
    flat = _fold(kind, _fresh(kind), stream)
    for n in (2, 4, 8):
        parts = [_fold(kind, _fresh(kind), stream[i::n]) for i in range(n)]
        assert _leaves_equal(flat, merge_all(parts)), n


@pytest.mark.parametrize("kind", ["hh", "distinct", "cooccur"])
def test_config_mismatch_refuses(kind):
    a = _fresh(kind)
    if kind == "hh":
        b = HeavyHitterSketch(capacity=32, depth=4, id_bits=16)
    elif kind == "distinct":
        b = DistinctCountSketch(precision=9)
    else:
        b = CoOccurrenceSketch(num_rows=300, num_cols=300, capacity=32, depth=4)
    with pytest.raises(ValueError, match="config"):
        a.merge(b)


# ---------------------------------------------------------------------------
# jit / scan / vmap carry + pack-tree round trip
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", ["hh", "distinct", "cooccur"])
def test_jit_scan_fold_matches_eager(kind, stream):
    eager = _fold(kind, _fresh(kind), stream[:512])

    jitted = jax.jit(lambda sk, xs: _fold(kind, sk, xs))
    assert _leaves_equal(eager, jitted(_fresh(kind), stream[:512]))

    def body(carry, xs):
        return _fold(kind, carry, xs), None

    scanned, _ = jax.lax.scan(body, _fresh(kind), jnp.asarray(stream[:512]).reshape(8, 64))
    assert _leaves_equal(eager, scanned)


@pytest.mark.parametrize("kind", ["hh", "distinct", "cooccur"])
def test_stack_reduce_leading_axis(kind, stream):
    """The vmap/make_epoch contract: per-slot folds reduce back down to
    the plain merge of the slots."""
    parts = [_fold(kind, _fresh(kind), stream[i::4]) for i in range(4)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *parts)
    assert _leaves_equal(merge_all(parts), stacked.reduce_leading_axis())


@pytest.mark.parametrize("kind", ["hh", "distinct", "cooccur"])
def test_pack_tree_roundtrip_bitwise(kind, stream):
    sk = _fold(kind, _fresh(kind), stream)
    back = sketch_from_pack_tree(sk.to_pack_tree())
    assert type(back) is type(sk)
    assert back.config() == sk.config()
    assert _leaves_equal(sk, back)


# ---------------------------------------------------------------------------
# 1M-sample oracles vs exact references
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def big_zipf():
    rng = np.random.default_rng(42)
    return (rng.zipf(1.3, N_BIG) % 100_000).astype(np.int64)


def test_heavy_hitter_1m_oracle(big_zipf):
    """At 1M zipf samples over 100k ids: the reported top-k ids are the
    exact top-k, every reported count is >= the true count (SpaceSaving
    contract), and the truth sits inside the overestimate envelope."""
    sk = HeavyHitterSketch(capacity=256, depth=4, id_bits=24)
    for lo in range(0, N_BIG, 250_000):
        sk = sk.fold(jnp.asarray(big_zipf[lo : lo + 250_000]))
    exact = collections.Counter(big_zipf.tolist())
    k = 20
    ids, counts, over = (np.asarray(x) for x in sk.topk(k))
    assert int(np.asarray(sk.count)) == N_BIG
    expected = [i for i, _ in exact.most_common(k)]
    assert set(ids.tolist()) == set(expected)
    for i in range(k):
        truth = exact[int(ids[i])]
        assert counts[i] >= truth - 1e-6, (ids[i], counts[i], truth)
        assert counts[i] - over[i] <= truth + 1e-6, (ids[i], counts[i], over[i], truth)


def test_heavy_hitter_frequency_bounds_rigorous(big_zipf):
    """frequency_bounds() contains the exact count for arbitrary queried
    ids — including ids never folded (bound must admit 0)."""
    sk = HeavyHitterSketch(capacity=256, depth=4, id_bits=24)
    sk = sk.fold(jnp.asarray(big_zipf[:200_000]))
    exact = collections.Counter(big_zipf[:200_000].tolist())
    query = np.asarray([0, 1, 2, 3, 17, 999, 54_321, 99_999], dtype=np.int64)
    lo, hi = (np.asarray(x) for x in sk.frequency_bounds(jnp.asarray(query)))
    for q, l, h in zip(query.tolist(), lo.tolist(), hi.tolist()):
        assert l - 1e-6 <= exact.get(q, 0) <= h + 1e-6, (q, l, h, exact.get(q, 0))


def test_distinct_count_1m_oracle():
    """HLL at p=12 over three cardinality regimes: estimate within the
    3-sigma standard-error envelope of the exact distinct count."""
    rng = np.random.default_rng(5)
    for n_unique in (500, 60_000, N_BIG):
        ids = rng.integers(0, n_unique, size=max(n_unique * 2, 1000), dtype=np.int64)
        exact = len(np.unique(ids))
        sk = DistinctCountSketch(precision=12)
        for lo in range(0, len(ids), 500_000):
            sk = sk.fold(jnp.asarray(ids[lo : lo + 500_000]))
        est = float(sk.estimate())
        sigma = float(sk.relative_error())
        assert abs(est / exact - 1.0) <= 3 * sigma, (n_unique, est, exact)


def test_cooccur_1m_oracle():
    """1M (row, col) pairs over a 5000x5000 label space: top-cell set is
    the exact top set, counts never underestimate, and the collision
    envelope contains the exact count for every reported and queried
    cell."""
    rng = np.random.default_rng(9)
    rows = (rng.zipf(1.6, N_BIG) % 5000).astype(np.int64)
    noise = rng.integers(0, 5000, N_BIG)
    cols = np.where(rng.random(N_BIG) < 0.8, rows, noise).astype(np.int64)
    sk = CoOccurrenceSketch(num_rows=5000, num_cols=5000, capacity=256, depth=4)
    for lo in range(0, N_BIG, 250_000):
        sk = sk.fold(jnp.asarray(rows[lo : lo + 250_000]), jnp.asarray(cols[lo : lo + 250_000]))
    exact = collections.Counter(zip(rows.tolist(), cols.tolist()))
    k = 10
    rr, cc, counts, over = (np.asarray(x) for x in sk.top_cells(k))
    expected = {cell for cell, _ in exact.most_common(k)}
    assert {(int(r), int(c)) for r, c in zip(rr, cc)} == expected
    for i in range(k):
        truth = exact[(int(rr[i]), int(cc[i]))]
        assert counts[i] >= truth - 1e-6
        assert counts[i] - over[i] <= truth + 1e-6
    # marginals are exact
    row_marg = np.asarray(sk.row_marg)
    exact_marg = np.bincount(rows, minlength=5000).astype(np.float64)
    assert np.array_equal(row_marg, exact_marg)
    # arbitrary cell queries bounded
    q = 50
    qr, qc = rows[:q], cols[:q]
    lo_b, hi_b = (np.asarray(x) for x in sk.cell_bounds(jnp.asarray(qr), jnp.asarray(qc)))
    for i in range(q):
        truth = exact[(int(qr[i]), int(qc[i]))]
        assert lo_b[i] - 1e-6 <= truth <= hi_b[i] + 1e-6


# ---------------------------------------------------------------------------
# streaming metrics on top
# ---------------------------------------------------------------------------


class TestStreamingMetrics:
    def test_topk_metric_contract(self, stream):
        m = StreamingTopK(k=5, capacity=64, id_bits=16)
        m.update(jnp.asarray(stream))
        ids, counts = m.compute()
        exact = collections.Counter(stream.tolist())
        err = np.asarray(m.error_bound())
        for i, c, e in zip(np.asarray(ids), np.asarray(counts), err):
            truth = exact.get(int(i), 0)
            assert c >= truth - 1e-6
            assert c - e <= truth + 1e-6
        lo, hi = m.bounds()
        assert np.array_equal(np.asarray(hi), np.asarray(counts))

    def test_distinct_metric_contract(self):
        m = StreamingDistinctCount(precision=12)
        m.update(jnp.arange(50_000))
        est = float(m.compute())
        assert abs(est - 50_000) <= float(m.error_bound()) * 1.5  # 3-sigma
        lo, hi = m.bounds()
        assert float(lo) <= est <= float(hi)

    def test_confusion_metric_contract(self, stream):
        m = StreamingConfusion(num_rows=300, k=4, capacity=64)
        t, p = stream % 300, (stream * 13) % 300
        m.update(jnp.asarray(t), jnp.asarray(p))
        rows, cols, counts = m.compute()
        exact = collections.Counter(zip(t.tolist(), p.tolist()))
        err = np.asarray(m.error_bound())
        for r, c, n, e in zip(np.asarray(rows), np.asarray(cols), np.asarray(counts), err):
            truth = exact.get((int(r), int(c)), 0)
            assert n >= truth - 1e-6
            assert n - e <= truth + 1e-6
        lo, hi = m.cell_bounds(jnp.asarray(t[:20]), jnp.asarray(p[:20]))
        for i in range(20):
            truth = exact[(int(t[i]), int(p[i]))]
            assert float(lo[i]) - 1e-6 <= truth <= float(hi[i]) + 1e-6

    def test_certified_topk_and_churn(self):
        # interval a: {7, 9} dominate; interval b: 3 overtakes 9
        a = StreamingTopK(k=2, capacity=64, id_bits=16)
        a.update(jnp.asarray([7] * 10 + [9] * 8 + [3] * 1))
        b = StreamingTopK(k=2, capacity=64, id_bits=16)
        b.update(jnp.asarray([7] * 12 + [9] * 8 + [3] * 20))
        assert sorted(int(i) for i in a.certified_topk()) == [7, 9]
        assert StreamingTopK.churn(a, b) == {
            "entered": [3],
            "exited": [9],
            "stayed": [7],
        }

    def test_churn_never_evicted_is_exact(self):
        # fewer distinct ids than capacity: membership is exact even
        # though the (k+1)-th slot is empty
        a = StreamingTopK(k=3, capacity=64, id_bits=16)
        a.update(jnp.asarray([1, 1, 2]))
        assert sorted(int(i) for i in a.certified_topk()) == [1, 2]

    def test_churn_refuses_ambiguous_membership(self):
        # a saturated width-1 sketch: evictions inflate overestimates
        # until the k-th lower bound cannot clear the (k+1)-th upper
        rng = np.random.default_rng(0)
        m = StreamingTopK(k=2, capacity=4, depth=1, id_bits=16)
        m.update(jnp.asarray(rng.integers(0, 5000, 4096)))
        with pytest.raises(ChurnUndefinedError, match="ambiguous"):
            m.certified_topk()

    def test_churn_validates_operands(self):
        a = StreamingTopK(k=2, capacity=64, id_bits=16)
        b = StreamingTopK(k=3, capacity=64, id_bits=16)
        with pytest.raises(ValueError, match="matching k"):
            a.churn(b)
        with pytest.raises(ValueError, match="two StreamingTopK"):
            a.churn("not a metric")

    def test_metric_reset_and_weighted_update(self, stream):
        m = StreamingTopK(k=3, capacity=64, id_bits=16)
        m.update(jnp.asarray([5, 5, 9]), jnp.asarray([2.0, 3.0, 4.0]))
        ids, counts = m.compute()
        got = dict(zip(np.asarray(ids).tolist(), np.asarray(counts).tolist()))
        assert got[5] == 5.0 and got[9] == 4.0
        m.reset()
        ids, counts = m.compute()
        assert np.asarray(counts).sum() == 0.0
