"""Sketch merge algebra + error-bound pins.

The whole streaming subsystem stands on two properties of the sketches:

1. ``merge`` is an exact monoid — associative, commutative, fresh sketch
   as identity — BITWISE, across any shard count and fold order (this is
   what makes mesh merges order-invariant and preemption-resume replays
   reproducible).
2. the documented error bounds hold against exact NumPy/sklearn answers
   on large (1M-sample) synthetic streams.
"""
import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.streaming import (
    QuantileSketch,
    ScoreLabelSketch,
    merge_all,
    sketch_from_pack_tree,
)

N_BIG = 1_000_000


def _leaves_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )


def _shard_sketches(kind, data, n_shards):
    rng = np.random.default_rng(7)
    bounds = np.sort(rng.choice(np.arange(1, len(data[0])), size=n_shards - 1, replace=False))
    pieces = []
    start = 0
    for end in list(bounds) + [len(data[0])]:
        if kind == "quantile":
            sk = QuantileSketch(num_bins=64, lo=0.0, hi=1.0).fold(jnp.asarray(data[0][start:end]))
        else:
            sk = ScoreLabelSketch(num_bins=64).fold(
                jnp.asarray(data[0][start:end]), jnp.asarray(data[1][start:end])
            )
        pieces.append(sk)
        start = end
    return pieces


@pytest.fixture(scope="module")
def stream():
    rng = np.random.default_rng(3)
    preds = rng.uniform(0, 1, 4096).astype(np.float32)
    target = rng.integers(0, 2, 4096).astype(np.int32)
    return preds, target


@pytest.mark.parametrize("kind", ["quantile", "scorelabel"])
@pytest.mark.parametrize("n_shards", [2, 3, 5, 8])
def test_merge_associative_commutative_bitwise(kind, n_shards, stream):
    """Every parenthesization and permutation of shard merges produces the
    SAME sketch, bitwise (uneven shard sizes included)."""
    pieces = _shard_sketches(kind, stream, n_shards)
    reference = merge_all(pieces)
    # commutativity + associativity: every permutation, left fold
    for perm in itertools.islice(itertools.permutations(range(n_shards)), 12):
        assert _leaves_equal(reference, merge_all([pieces[i] for i in perm]))
    # a different association: pairwise tree fold
    level = list(pieces)
    while len(level) > 1:
        level = [
            level[i].merge(level[i + 1]) if i + 1 < len(level) else level[i]
            for i in range(0, len(level), 2)
        ]
    assert _leaves_equal(reference, level[0])


@pytest.mark.parametrize("kind", ["quantile", "scorelabel"])
def test_merge_identity(kind, stream):
    """A fresh sketch is the merge identity, on either side."""
    preds, target = stream
    if kind == "quantile":
        full = QuantileSketch(num_bins=64).fold(jnp.asarray(preds))
        fresh = QuantileSketch(num_bins=64)
    else:
        full = ScoreLabelSketch(num_bins=64).fold(jnp.asarray(preds), jnp.asarray(target))
        fresh = ScoreLabelSketch(num_bins=64)
    assert _leaves_equal(full, full.merge(fresh))
    assert _leaves_equal(full, fresh.merge(full))


def test_merge_config_mismatch_raises(stream):
    with pytest.raises(ValueError, match="different configs"):
        QuantileSketch(num_bins=64).merge(QuantileSketch(num_bins=32))
    with pytest.raises(ValueError, match="cannot merge"):
        QuantileSketch(num_bins=64).merge(ScoreLabelSketch(num_bins=64))


def test_sharded_fold_equals_single_fold(stream):
    """Merging per-shard folds == one fold over the concatenation (the
    make_epoch / DDP equivalence), bitwise for integer-valued counts."""
    preds, target = stream
    whole = ScoreLabelSketch(num_bins=64).fold(jnp.asarray(preds), jnp.asarray(target))
    merged = merge_all(_shard_sketches("scorelabel", stream, 4))
    assert _leaves_equal(whole, merged)


def test_quantile_error_bound_1m():
    """|quantile() - exact NumPy quantile| <= the computable envelope
    half-width at 1M samples, for several distributions and ranks."""
    rng = np.random.default_rng(11)
    qs = np.asarray([0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99], np.float32)
    for name, values in {
        "uniform": rng.uniform(0, 1, N_BIG),
        "beta": rng.beta(2.0, 5.0, N_BIG),
        "clipped_normal": np.clip(rng.normal(0.5, 0.2, N_BIG), 0, 1),
    }.items():
        values = values.astype(np.float32)
        sk = QuantileSketch(num_bins=1024, lo=0.0, hi=1.0).fold(jnp.asarray(values))
        got = np.asarray(sk.quantile(jnp.asarray(qs)))
        lo, hi = (np.asarray(a) for a in sk.quantile_bounds(jnp.asarray(qs)))
        exact = np.quantile(values, qs).astype(np.float32)
        half = (hi - lo) / 2
        assert np.all(half <= (1.0 / 1024) / 2 + 1e-6), name  # in-range data
        assert np.all(np.abs(got - exact) <= half + 1e-5), (name, got, exact, half)
        # exact value inside the rigorous envelope
        assert np.all(exact >= lo - 1e-5) and np.all(exact <= hi + 1e-5), name


def test_quantile_bound_holds_on_skewed_mass():
    """The half-width contract on adversarially skewed data: nearly all
    mass is one repeated value at a bin's low edge, so a rank-interpolated
    estimate would land anywhere in the bin while the exact quantile sits
    at its edge — only the envelope midpoint keeps |est - exact| within
    the half-width."""
    values = np.asarray([0.05] + [0.41] * 100 + [0.95], np.float32)
    sk = QuantileSketch(num_bins=10, lo=0.0, hi=1.0).fold(jnp.asarray(values))
    q = 100 / 102
    est = float(sk.quantile(q))
    exact = float(np.quantile(values, q))
    lo, hi = (float(x[0]) for x in sk.quantile_bounds(jnp.asarray([q])))
    assert lo - 1e-6 <= exact <= hi + 1e-6
    assert abs(est - exact) <= (hi - lo) / 2 + 1e-6


def test_quantile_out_of_range_mass():
    """Out-of-range values land in the min/max-edged overflow bins; extreme
    quantiles stay exact at the observed extremes."""
    values = np.concatenate([np.full(10, -3.0), np.linspace(0, 1, 80), np.full(10, 7.0)]).astype(
        np.float32
    )
    sk = QuantileSketch(num_bins=16, lo=0.0, hi=1.0).fold(jnp.asarray(values))
    assert float(sk.quantile(0.0)) == -3.0
    assert float(sk.quantile(1.0)) == 7.0
    lo, hi = sk.quantile_bounds(jnp.asarray([0.05]))
    assert float(lo[0]) == -3.0  # underflow bin spans [min, lo]


def test_auroc_ap_error_bound_1m():
    """|sketch value - exact sklearn value| <= the computable half-width at
    1M samples, and the exact value sits inside the rigorous envelope."""
    sklearn_metrics = pytest.importorskip("sklearn.metrics")
    rng = np.random.default_rng(13)
    preds = rng.uniform(0, 1, N_BIG).astype(np.float32)
    target = (rng.uniform(0, 1, N_BIG) < 0.2 + 0.6 * preds).astype(np.int32)
    exact_auroc = sklearn_metrics.roc_auc_score(target, preds)
    exact_ap = sklearn_metrics.average_precision_score(target, preds)

    sk = ScoreLabelSketch(num_bins=2048).fold(jnp.asarray(preds), jnp.asarray(target))
    lo, hi = (float(x) for x in sk.auroc_bounds())
    assert lo - 1e-6 <= exact_auroc <= hi + 1e-6
    assert abs(float(sk.auroc()) - exact_auroc) <= float(sk.auroc_error_bound()) + 1e-6
    assert float(sk.auroc_error_bound()) < 5e-3  # tight at 2048 bins

    lo, hi = (float(x) for x in sk.average_precision_bounds())
    assert lo - 1e-5 <= exact_ap <= hi + 1e-5
    assert abs(float(sk.average_precision()) - exact_ap) <= float(
        sk.average_precision_error_bound()
    ) + 1e-5
    assert float(sk.average_precision_error_bound()) < 5e-3


def test_scorelabel_extreme_orderings():
    """Perfectly separated and perfectly inverted streams hit the envelope
    ends exactly (no same-bin pairs -> zero-width envelope)."""
    preds = jnp.asarray([0.1, 0.2, 0.8, 0.9])
    sk = ScoreLabelSketch(num_bins=16).fold(preds, jnp.asarray([0, 0, 1, 1]))
    assert float(sk.auroc()) == 1.0 and float(sk.auroc_error_bound()) == 0.0
    assert float(sk.average_precision()) == pytest.approx(1.0)
    sk = ScoreLabelSketch(num_bins=16).fold(preds, jnp.asarray([1, 1, 0, 0]))
    assert float(sk.auroc()) == 0.0


def test_sketch_jit_scan_vmap_carry(stream):
    """Sketches are valid jit/scan/vmap carries: folding under lax.scan
    equals the eager fold, bitwise."""
    preds, target = stream
    p = jnp.asarray(preds[:4000].reshape(8, 500))
    t = jnp.asarray(target[:4000].reshape(8, 500))

    def body(sk, batch):
        return sk.fold(batch[0], batch[1]), None

    scanned, _ = jax.lax.scan(body, ScoreLabelSketch(num_bins=64), (p, t))
    eager = ScoreLabelSketch(num_bins=64).fold(p.reshape(-1), t.reshape(-1))
    assert _leaves_equal(scanned, eager)

    # vmap per-batch folds, then reduce the stacked axis = same state
    stacked = jax.vmap(lambda pb, tb: ScoreLabelSketch(num_bins=64).fold(pb, tb))(p, t)
    assert _leaves_equal(stacked.reduce_leading_axis(), eager)


def test_slot_ops_roundtrip(stream):
    """stack/slot/set_slot/merge_into_slot are consistent (ring plumbing)."""
    preds, target = stream
    base = ScoreLabelSketch(num_bins=32)
    row = base.fold(jnp.asarray(preds[:100]), jnp.asarray(target[:100]))
    ring = base.stack(4).set_slot(2, row)
    assert _leaves_equal(ring.slot(2), row)
    assert _leaves_equal(ring.slot(0), base)
    merged = ring.merge_into_slot(2, row)
    assert _leaves_equal(merged.slot(2), row.merge(row))
    assert _leaves_equal(ring.reduce_leading_axis(), row)  # 3 identity slots


def test_pack_tree_roundtrip(stream):
    """Checkpoint packing reconstructs class, config and leaves exactly —
    including from numpy leaves (the orbax restore shape)."""
    preds, target = stream
    for sk in (
        QuantileSketch(num_bins=48, lo=-2.0, hi=3.0).fold(jnp.asarray(preds)),
        ScoreLabelSketch(num_bins=96).fold(jnp.asarray(preds), jnp.asarray(target)),
    ):
        packed = sk.to_pack_tree()
        packed_np = {k: np.asarray(v) for k, v in packed.items()}
        restored = sketch_from_pack_tree(packed_np)
        assert type(restored) is type(sk)
        assert restored.config() == sk.config()
        assert _leaves_equal(restored, sk)


def test_scale_sum_leaves():
    """Decay scales counts but never the min/max extremes."""
    sk = QuantileSketch(num_bins=8, lo=0.0, hi=1.0).fold(jnp.asarray([0.1, 0.9]))
    scaled = sk.scale_sum_leaves(0.5)
    assert float(scaled.counts.sum()) == pytest.approx(1.0)
    assert float(scaled.minv) == pytest.approx(0.1)
    assert float(scaled.maxv) == pytest.approx(0.9)


@pytest.mark.parametrize("num_bins", [100, 128, 193])
def test_fold_arms_agree(stream, num_bins):
    """The kernel-backed fold arm (ops.binned_label_histograms, via the
    fused threshold kernel) and the scatter-add bincount arm produce
    IDENTICAL histograms — including the 0.0/1.0 edge bins and EVERY f32
    bin-boundary score at non-power-of-two bin counts, where `int(v*T)`
    truncation would disagree with the kernel's `v >= k/T` comparison — so
    the backend-dependent arm selection can never change sketch state."""
    from metrics_tpu.ops.binned_counts import binned_label_histograms

    preds, target = stream
    boundaries = np.arange(num_bins, dtype=np.float32) / num_bins
    preds = np.concatenate([preds, boundaries, [0.0, 1.0]]).astype(np.float32)
    rng = np.random.default_rng(1)
    target = rng.integers(0, 2, len(preds)).astype(np.int32)
    sk = ScoreLabelSketch(num_bins=num_bins)
    ph, nh = sk._hists_via_bincount(jnp.asarray(preds), jnp.asarray(target) == 1)
    ph2, nh2 = binned_label_histograms(jnp.asarray(preds), jnp.asarray(target), num_bins)
    assert np.array_equal(np.asarray(ph), np.asarray(ph2))
    assert np.array_equal(np.asarray(nh), np.asarray(nh2))


def test_nbytes_budget():
    """The acceptance budget: a 2048-bin score/label sketch is 16 KB."""
    assert ScoreLabelSketch(num_bins=2048).nbytes == 2 * 2048 * 4
    assert QuantileSketch(num_bins=1024).nbytes == (1024 + 2) * 4 + 8
