"""StreamingAUROC / StreamingAveragePrecision / StreamingQuantile:
bounded-memory accuracy, lifecycle integration, mesh order-invariance and
checkpoint resume — the acceptance pins of the streaming subsystem.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import Accuracy, MetricCollection
from metrics_tpu.steps import make_epoch, make_step
from metrics_tpu.streaming import (
    StreamingAUROC,
    StreamingAveragePrecision,
    StreamingQuantile,
)
from metrics_tpu.utilities.distributed import sync_sketch_in_context

try:
    from jax import shard_map as _shard_map_mod  # noqa: F401  jax>=0.6 style

    def shard_map(f, mesh, in_specs, out_specs):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)

except ImportError:
    from jax.experimental.shard_map import shard_map as _sm

    def shard_map(f, mesh, in_specs, out_specs):
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)

N_DEV = 8
N_BIG = 1_000_000


@pytest.fixture(scope="module")
def big_stream():
    rng = np.random.default_rng(5)
    preds = rng.uniform(0, 1, N_BIG).astype(np.float32)
    target = (rng.uniform(0, 1, N_BIG) < 0.25 + 0.5 * preds).astype(np.int32)
    return preds, target


def test_streaming_auroc_1m_error_bound_and_state_budget(big_stream):
    """ACCEPTANCE: 1M streamed samples stay within the documented error
    bound of exact AUROC while device state holds <= 64 KB."""
    sklearn_metrics = pytest.importorskip("sklearn.metrics")
    preds, target = big_stream
    m = StreamingAUROC()  # default 2048 bins
    for i in range(0, N_BIG, 100_000):  # streamed in 10 batches
        m.update(jnp.asarray(preds[i : i + 100_000]), jnp.asarray(target[i : i + 100_000]))
    exact = sklearn_metrics.roc_auc_score(target, preds)
    got = float(m.compute())
    bound = float(m.error_bound())
    assert abs(got - exact) <= bound + 1e-6
    assert bound < 5e-3
    assert m.sketch.nbytes <= 64 * 1024
    lo, hi = (float(x) for x in m.bounds())
    assert lo - 1e-6 <= exact <= hi + 1e-6


def test_streaming_ap_1m_error_bound(big_stream):
    sklearn_metrics = pytest.importorskip("sklearn.metrics")
    preds, target = big_stream
    m = StreamingAveragePrecision()
    m.update(jnp.asarray(preds), jnp.asarray(target))
    exact = sklearn_metrics.average_precision_score(target, preds)
    assert abs(float(m.compute()) - exact) <= float(m.error_bound()) + 1e-5
    assert m.sketch.nbytes <= 64 * 1024


def test_streaming_quantile_1m(big_stream):
    preds, _ = big_stream
    m = StreamingQuantile(q=[0.1, 0.5, 0.9], num_bins=1024)
    m.update(jnp.asarray(preds))
    exact = np.quantile(preds, [0.1, 0.5, 0.9])
    got = np.asarray(m.compute())
    bound = np.asarray(m.error_bound())
    assert np.all(np.abs(got - exact) <= bound + 1e-5)
    assert np.all(bound <= (1.0 / 1024) / 2 + 1e-6)


def test_scalar_quantile_shape():
    m = StreamingQuantile(q=0.5, num_bins=64)
    m.update(jnp.linspace(0, 1, 101))
    assert jnp.ndim(m.compute()) == 0
    assert float(m.compute()) == pytest.approx(0.5, abs=1e-2)


def test_streamed_equals_single_update_bitwise(big_stream):
    """Batched streaming == one update over the concatenation, bitwise —
    the merge-fold property the fused epoch path relies on."""
    preds, target = big_stream
    a = StreamingAUROC(num_bins=256)
    for i in range(0, 50_000, 5_000):
        a.update(jnp.asarray(preds[i : i + 5_000]), jnp.asarray(target[i : i + 5_000]))
    b = StreamingAUROC(num_bins=256)
    b.update(jnp.asarray(preds[:50_000]), jnp.asarray(target[:50_000]))
    assert float(a.compute()) == float(b.compute())


def test_forward_returns_batch_value_and_accumulates(big_stream):
    preds, target = big_stream
    m = StreamingAUROC(num_bins=256)
    v1 = m(jnp.asarray(preds[:4_000]), jnp.asarray(target[:4_000]))
    batch_only = StreamingAUROC(num_bins=256)
    batch_only.update(jnp.asarray(preds[:4_000]), jnp.asarray(target[:4_000]))
    assert float(v1) == float(batch_only.compute())
    m(jnp.asarray(preds[4_000:8_000]), jnp.asarray(target[4_000:8_000]))
    full = StreamingAUROC(num_bins=256)
    full.update(jnp.asarray(preds[:8_000]), jnp.asarray(target[:8_000]))
    assert float(m.compute()) == float(full.compute())


def test_reset_restores_identity(big_stream):
    preds, target = big_stream
    m = StreamingAUROC(num_bins=64)
    m.update(jnp.asarray(preds[:1_000]), jnp.asarray(target[:1_000]))
    m.reset()
    assert float(m.sketch.count) == 0.0


def test_make_step_scan_parity(big_stream):
    preds, target = big_stream
    init, step, compute = make_step(StreamingAUROC, num_bins=256)
    p = jnp.asarray(preds[:8_000].reshape(8, 1_000))
    t = jnp.asarray(target[:8_000].reshape(8, 1_000))
    state, values = jax.lax.scan(lambda s, b: step(s, *b), init(), (p, t))
    eager = StreamingAUROC(num_bins=256)
    eager.update(p.reshape(-1), t.reshape(-1))
    assert float(compute(state)) == float(eager.compute())
    assert values.shape == (8,)


@pytest.mark.parametrize("with_values", [False, True])
def test_make_epoch_parity(big_stream, with_values):
    """Sketch states ride the fused epoch (flat/vmap) paths bitwise."""
    preds, target = big_stream
    init, epoch, compute = make_epoch(StreamingAUROC, num_bins=256, with_values=with_values)
    p = jnp.asarray(preds[:8_000].reshape(8, 1_000))
    t = jnp.asarray(target[:8_000].reshape(8, 1_000))
    state, values = epoch(init(), p, t)
    eager = StreamingAUROC(num_bins=256)
    eager.update(p.reshape(-1), t.reshape(-1))
    assert float(compute(state)) == float(eager.compute())
    if with_values:
        assert values.shape == (8,)


def test_mesh_merge_order_invariant_bitwise(big_stream):
    """ACCEPTANCE: the sketch state merges order-invariantly across mesh
    shards — permuting which device holds which shard leaves the merged
    state bitwise identical, and compute() equals the global eager value."""
    preds, target = big_stream
    mesh = Mesh(np.asarray(jax.devices()[:N_DEV]), ("dp",))
    init, step, compute = make_step(StreamingAUROC(num_bins=256), axis_name="dp")
    p = jnp.asarray(preds[: N_DEV * 1_000].reshape(N_DEV, 1_000))
    t = jnp.asarray(target[: N_DEV * 1_000].reshape(N_DEV, 1_000))

    def value_prog(pb, tb):
        state, _ = step(init(), pb[0], tb[0])
        return compute(state)

    fn = jax.jit(shard_map(value_prog, mesh, in_specs=(P("dp"), P("dp")), out_specs=P()))
    eager = StreamingAUROC(num_bins=256)
    eager.update(p.reshape(-1), t.reshape(-1))
    assert float(fn(p, t)) == float(eager.compute())

    def state_prog(pb, tb):
        state, _ = step(init(), pb[0], tb[0])
        return sync_sketch_in_context(state["sketch"], "dp")

    sfn = jax.jit(shard_map(state_prog, mesh, in_specs=(P("dp"), P("dp")), out_specs=P()))
    merged = sfn(p, t)
    for perm in ([7, 6, 5, 4, 3, 2, 1, 0], [3, 1, 7, 0, 5, 2, 6, 4]):
        permuted = sfn(p[np.asarray(perm)], t[np.asarray(perm)])
        for a, b in zip(jax.tree_util.tree_leaves(merged), jax.tree_util.tree_leaves(permuted)):
            assert np.array_equal(np.asarray(a), np.asarray(b))


def test_eager_sync_merges_sketches(big_stream):
    """The eager DCN gather path: a simulated 2-rank dist_sync_fn merges
    per-rank sketches into the global one (parity with pooled data)."""
    preds, target = big_stream
    p0, t0 = jnp.asarray(preds[:3_000]), jnp.asarray(target[:3_000])
    p1, t1 = jnp.asarray(preds[3_000:5_000]), jnp.asarray(target[3_000:5_000])
    other = StreamingAUROC(num_bins=128)
    other.update(p1, t1)
    other_leaves = jax.tree_util.tree_leaves(other.sketch)

    rank_i = [0]

    def fake_gather(x, group=None):
        out = [x, other_leaves[rank_i[0] % len(other_leaves)]]
        rank_i[0] += 1
        return out

    m = StreamingAUROC(num_bins=128, dist_sync_fn=fake_gather)
    m.update(p0, t0)
    with m.sync_context(distributed_available_fn=lambda: True):
        synced = float(m.sketch.auroc())
    pooled = StreamingAUROC(num_bins=128)
    pooled.update(jnp.asarray(preds[:5_000]), jnp.asarray(target[:5_000]))
    assert synced == float(pooled.compute())
    # unsync restored the local-only state
    local = StreamingAUROC(num_bins=128)
    local.update(p0, t0)
    assert float(m.compute.__wrapped__(m)) == float(local.compute.__wrapped__(local))


def test_collection_membership_and_compute_groups(big_stream):
    preds, target = big_stream
    coll = MetricCollection(
        [StreamingAUROC(num_bins=128), StreamingAveragePrecision(num_bins=128)]
    )
    coll.update(jnp.asarray(preds[:2_000]), jnp.asarray(target[:2_000]))
    coll.update(jnp.asarray(preds[2_000:4_000]), jnp.asarray(target[2_000:4_000]))
    res = coll.compute()
    # identical sketch states -> one compute group, values still distinct
    assert len(coll.compute_groups) == 1
    ref = StreamingAUROC(num_bins=128)
    ref.update(jnp.asarray(preds[:4_000]), jnp.asarray(target[:4_000]))
    assert float(res["StreamingAUROC"]) == float(ref.compute())

    cinit, cstep, ccompute = make_step(coll)
    state, _ = cstep(cinit(), jnp.asarray(preds[:4_000]), jnp.asarray(target[:4_000]))
    out = ccompute(state)
    assert float(out["StreamingAUROC"]) == float(ref.compute())


def test_checkpoint_manager_roundtrip_bitwise(tmp_path, big_stream):
    """Kill-and-resume through ft.CheckpointManager: restored sketch metric
    continues the stream and reproduces compute() bitwise."""
    preds, target = big_stream
    from metrics_tpu.ft import BatchJournal, CheckpointManager

    mgr = CheckpointManager(os.path.join(tmp_path, "ck"))
    journal = BatchJournal()
    m = StreamingAUROC(num_bins=256)
    m.update(jnp.asarray(preds[:2_000]), jnp.asarray(target[:2_000]))
    journal.record(0, 0)
    mgr.save(m, journal=journal, epoch=0, step=0)

    resumed = StreamingAUROC(num_bins=256)
    j2 = BatchJournal()
    manifest = mgr.restore(resumed, journal=j2)
    assert manifest["journal"]["watermark"] == [0, 0]
    assert not j2.should_fold(0, 0)  # exactly-once: batch 0 never refolds
    assert j2.should_fold(0, 1)
    assert resumed._update_count == m._update_count

    for metric in (m, resumed):
        metric.update(jnp.asarray(preds[2_000:4_000]), jnp.asarray(target[2_000:4_000]))
    assert float(m.compute()) == float(resumed.compute())


def test_metric_save_restore_bitwise(tmp_path, big_stream):
    preds, target = big_stream
    m = StreamingAveragePrecision(num_bins=128)
    m.update(jnp.asarray(preds[:2_000]), jnp.asarray(target[:2_000]))
    m.save(tmp_path / "snap")
    other = StreamingAveragePrecision(num_bins=128).restore(tmp_path / "snap")
    assert float(m.compute()) == float(other.compute())


def test_set_dtype_leaves_sketch_counts_exact(big_stream):
    preds, target = big_stream
    m = StreamingAUROC(num_bins=64)
    m.update(jnp.asarray(preds[:1_000]), jnp.asarray(target[:1_000]))
    before = float(m.compute())
    m.half()
    m.update(jnp.asarray(preds[1_000:1_001]), jnp.asarray(target[1_000:1_001]))
    assert m.sketch.pos.dtype == jnp.float32  # counts stay exact-integer f32
    assert isinstance(before, float)


def test_add_state_sketch_validation():
    from metrics_tpu.metric import Metric
    from metrics_tpu.streaming import ScoreLabelSketch

    class Bad(Metric):
        def __init__(self):
            super().__init__()
            self.add_state("s", default=ScoreLabelSketch(8), dist_reduce_fx="sum")

        def update(self):  # pragma: no cover - never reached
            pass

        def compute(self):  # pragma: no cover
            pass

    with pytest.raises(ValueError, match="dist_reduce_fx='sketch' or None"):
        Bad()

    class Bad2(Metric):
        def __init__(self):
            super().__init__()
            self.add_state("s", default=jnp.zeros(4), dist_reduce_fx="sketch")

        def update(self):  # pragma: no cover
            pass

        def compute(self):  # pragma: no cover
            pass

    with pytest.raises(ValueError, match="requires a streaming.sketches.Sketch"):
        Bad2()
