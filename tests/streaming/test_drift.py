"""Drift monitors: divergence math, threshold alerts, obs accounting."""
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import obs
from metrics_tpu.streaming import (
    DriftMonitor,
    QuantileSketch,
    ScoreLabelSketch,
    StreamingAUROC,
    js_divergence,
    kl_divergence,
    population_stability_index,
)


@pytest.fixture(scope="module")
def sketches():
    rng = np.random.default_rng(21)
    base = rng.uniform(0, 1, 20_000).astype(np.float32)
    ref = QuantileSketch(num_bins=64).fold(jnp.asarray(base[:10_000]))
    same = QuantileSketch(num_bins=64).fold(jnp.asarray(base[10_000:]))
    shifted = QuantileSketch(num_bins=64).fold(jnp.asarray(base[:10_000] * 0.3))
    return ref, same, shifted


def test_divergences_zero_for_identical(sketches):
    ref, _, _ = sketches
    assert float(population_stability_index(ref, ref)) == pytest.approx(0.0, abs=1e-6)
    assert float(kl_divergence(ref, ref)) == pytest.approx(0.0, abs=1e-6)
    assert float(js_divergence(ref, ref)) == pytest.approx(0.0, abs=1e-6)


def test_divergences_orderings(sketches):
    ref, same, shifted = sketches
    assert float(population_stability_index(ref, shifted)) > float(
        population_stability_index(ref, same)
    )
    # PSI and JS symmetric; KL not
    assert float(population_stability_index(ref, shifted)) == pytest.approx(
        float(population_stability_index(shifted, ref)), rel=1e-5
    )
    assert float(js_divergence(ref, shifted)) == pytest.approx(
        float(js_divergence(shifted, ref)), rel=1e-5
    )
    assert float(js_divergence(ref, shifted)) <= float(np.log(2)) + 1e-6


def test_divergences_against_numpy(sketches):
    """Pin the formulas against a direct NumPy evaluation of the masses."""
    ref, _, shifted = sketches
    eps = 1e-6
    p = np.asarray(shifted.bin_masses()) + eps
    p /= p.sum()
    q = np.asarray(ref.bin_masses()) + eps
    q /= q.sum()
    assert float(population_stability_index(ref, shifted)) == pytest.approx(
        float(((p - q) * np.log(p / q)).sum()), rel=1e-4
    )
    assert float(kl_divergence(ref, shifted)) == pytest.approx(
        float((p * np.log(p / q)).sum()), rel=1e-4
    )


def test_divergences_jit_safe(sketches):
    ref, _, shifted = sketches
    fn = jax.jit(lambda a, b: population_stability_index(a, b))
    assert float(fn(ref, shifted)) == pytest.approx(
        float(population_stability_index(ref, shifted)), rel=1e-6
    )


def test_monitor_alerts_and_counters(sketches):
    ref, same, shifted = sketches
    prev = obs.enable()
    obs.reset()
    try:
        mon = DriftMonitor(ref, psi_threshold=0.2, name="t", warn=False)
        ok = mon.check(same)
        assert not ok["alert"] and ok["triggered"] == []
        bad = mon.check(shifted)
        assert bad["alert"] and "psi" in bad["triggered"]
        assert obs.get_counter("stream.drift_checks", monitor="t") == 2
        assert obs.get_counter("stream.drift_alerts", monitor="t") == 1
    finally:
        obs.enable(prev)
        obs.reset()


def test_monitor_one_shot_warning(sketches):
    ref, _, shifted = sketches
    mon = DriftMonitor(ref, psi_threshold=0.1, name="warned")
    with pytest.warns(UserWarning, match="drifted past threshold"):
        mon.check(shifted)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # second alert must NOT warn again
        assert mon.check(shifted)["alert"]


def test_monitor_from_metric_reference():
    rng = np.random.default_rng(4)
    preds = rng.uniform(0, 1, 5_000).astype(np.float32)
    target = rng.integers(0, 2, 5_000).astype(np.int32)
    ref_metric = StreamingAUROC(num_bins=64)
    ref_metric.update(jnp.asarray(preds), jnp.asarray(target))
    live = StreamingAUROC(num_bins=64)
    live.update(jnp.asarray(preds * 0.2), jnp.asarray(target))
    mon = DriftMonitor(ref_metric, psi_threshold=0.2, warn=False)
    assert mon.check(live)["alert"]


def test_monitor_validation(sketches):
    ref, _, _ = sketches
    with pytest.raises(ValueError, match="at least one armed threshold"):
        DriftMonitor(ref, psi_threshold=None)
    with pytest.raises(ValueError, match="exactly one sketch state"):
        DriftMonitor(object())


def test_label_conditional_masses():
    """ScoreLabelSketch exposes class-conditional masses for per-class
    drift (e.g. score drift only among predicted positives)."""
    sk = ScoreLabelSketch(num_bins=4).fold(
        jnp.asarray([0.1, 0.1, 0.9, 0.9]), jnp.asarray([0, 0, 1, 1])
    )
    pos_m, neg_m = sk.label_masses()
    assert float(pos_m.sum()) == pytest.approx(1.0)
    assert float(neg_m.sum()) == pytest.approx(1.0)
    assert float(pos_m[-1]) == pytest.approx(1.0)  # positives all in top bin
    assert float(neg_m[0]) == pytest.approx(1.0)
