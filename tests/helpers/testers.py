"""MetricTester harness — oracle-comparison test runners.

TPU-native analogue of the reference's ``tests/helpers/testers.py:329-564``:
every metric is exercised (a) single-process through the stateful class API,
(b) under a **virtual DDP** of W in-process ranks whose cross-rank gather is a
fake ``dist_sync_fn`` wired between the rank metrics (replacing the
reference's 2-process gloo pool), and (c) as the pure functional form —
always compared against a trusted oracle (sklearn/numpy) on the concatenated
global data, proving sync-equivalence, not just no-crash.
"""
from collections import deque
from functools import partial
from typing import Any, Callable, Dict, Optional, Sequence

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat

NUM_PROCESSES = 2
NUM_BATCHES = 4
BATCH_SIZE = 32
NUM_CLASSES = 5
EXTRA_DIM = 3
THRESHOLD = 0.5


def _assert_allclose(tpu_result: Any, sk_result: Any, atol: float = 1e-8) -> None:
    """Recursively compare a metric result with the oracle."""
    if isinstance(tpu_result, dict):
        assert isinstance(sk_result, dict)
        for key in tpu_result:
            _assert_allclose(tpu_result[key], sk_result[key], atol=atol)
        return
    if isinstance(tpu_result, (list, tuple)) and not isinstance(sk_result, np.ndarray):
        assert len(tpu_result) == len(sk_result)
        for t, s in zip(tpu_result, sk_result):
            _assert_allclose(t, s, atol=atol)
        return
    np.testing.assert_allclose(np.asarray(tpu_result), np.asarray(sk_result), atol=atol, rtol=1e-5, equal_nan=True)


def _wire_virtual_ddp(metrics: Sequence[Metric]) -> None:
    """Connect in-process rank metrics with a fake cross-rank gather.

    Each rank's ``dist_sync_fn`` returns, for every state in declaration
    order, the list of that state's current value on every rank — exactly
    what ``gather_all_tensors`` would return across real processes.
    """
    queues: Dict[int, deque] = {id(m): deque() for m in metrics}

    def make_gather(m_self: Metric) -> Callable:
        def gather(x, group=None):
            q = queues[id(m_self)]
            if not q:
                if type(m_self)._sync_dist is Metric._sync_dist:
                    # base _sync_dist gathers only non-empty-list states
                    q.extend(
                        n
                        for n in m_self._reductions
                        if not (isinstance(getattr(m_self, n), list) and not getattr(m_self, n))
                    )
                else:
                    # custom _sync_dist overrides gather every state unconditionally
                    q.extend(m_self._reductions)
            name = q.popleft()
            out = []
            for m in metrics:
                v = getattr(m, name)
                is_catlike = isinstance(v, list) or hasattr(v, "materialize")
                if is_catlike and not v:
                    # peer rank saw no data: contribute an empty, dtype-matched chunk
                    out.append(jnp.zeros((0,) + tuple(x.shape[1:]), dtype=x.dtype))
                elif is_catlike:
                    out.append(dim_zero_cat(v))
                else:
                    out.append(v)
            return out

        return gather

    for m in metrics:
        m.dist_sync_fn = make_gather(m)
        m.distributed_available_fn = lambda: True


class MetricTester:
    """Base tester: single-device, virtual-DDP, and functional runners."""

    atol: float = 1e-8

    def run_functional_metric_test(
        self,
        preds: jnp.ndarray,
        target: jnp.ndarray,
        metric_functional: Callable,
        sk_metric: Callable,
        metric_args: Optional[dict] = None,
        **kwargs_update: Any,
    ) -> None:
        """Compare the functional form against the oracle per batch."""
        metric_args = metric_args or {}
        metric = partial(metric_functional, **metric_args)
        for i in range(NUM_BATCHES):
            extra = {k: v[i] for k, v in kwargs_update.items()}
            tpu_result = metric(preds[i], target[i], **extra)
            sk_result = sk_metric(preds[i], target[i], **extra)
            _assert_allclose(tpu_result, sk_result, atol=self.atol)

        # jit-compatibility: the analogue of the reference's TorchScript
        # scriptability assertion (testers.py:163-164) — every functional
        # kernel must trace under jax.jit (static input-case resolution, no
        # data-dependent python control flow) and match its eager value.
        import jax

        extra = {k: v[0] for k, v in kwargs_update.items()}
        try:
            jitted = jax.jit(metric)(preds[0], target[0], **extra)
        except ValueError as err:
            # inferring num_classes from label VALUES is a data-dependent
            # shape — the documented contract is an explicit error under jit
            if "under `jit`" not in str(err):
                raise
            return
        _assert_allclose(jitted, metric(preds[0], target[0], **extra), atol=self.atol)

    def run_class_metric_test(
        self,
        ddp: bool,
        preds: jnp.ndarray,
        target: jnp.ndarray,
        metric_class: type,
        sk_metric: Callable,
        dist_sync_on_step: bool = False,
        metric_args: Optional[dict] = None,
        check_dist_sync_on_step: bool = True,
        check_batch: bool = True,
        **kwargs_update: Any,
    ) -> None:
        """Full lifecycle test against the oracle.

        With ``ddp=True``, W=2 virtual ranks stride the batches (rank r gets
        batches r, r+W, ...); the final ``compute`` gathers all rank states
        through the real ``_sync_dist`` path and must match the oracle run on
        ALL data. With ``dist_sync_on_step``, the per-step value is checked
        against the oracle on the union of the step's per-rank batches.
        """
        metric_args = metric_args or {}
        world_size = NUM_PROCESSES if ddp else 1

        metrics = [metric_class(**metric_args) for _ in range(world_size)]

        # pickle round-trip before wiring (reference testers.py:174-175);
        # the fake gather closures are process-local and not picklable.
        import pickle

        pickle.loads(pickle.dumps(metrics[0]))

        if ddp:
            _wire_virtual_ddp(metrics)

        for i in range(NUM_BATCHES):
            if ddp and i % world_size != 0:
                continue
            batch_indices = list(range(i, min(i + world_size, NUM_BATCHES)))
            for rank, bi in enumerate(batch_indices):
                extra = {k: v[bi] for k, v in kwargs_update.items()}
                batch_result = metrics[rank].forward(preds[bi], target[bi], **extra)
                if check_batch and not dist_sync_on_step:
                    extra_np = {k: np.asarray(v[bi]) for k, v in kwargs_update.items()}
                    sk_batch_result = sk_metric(preds[bi], target[bi], **extra_np)
                    _assert_allclose(batch_result, sk_batch_result, atol=self.atol)

            if ddp and dist_sync_on_step and check_dist_sync_on_step:
                # Emulate the in-forward sync: fresh per-rank metrics updated
                # with this step's batches only, gathered via the real path.
                step_metrics = [metric_class(**metric_args) for _ in batch_indices]
                _wire_virtual_ddp(step_metrics)
                for rank, bi in enumerate(batch_indices):
                    extra = {k: v[bi] for k, v in kwargs_update.items()}
                    step_metrics[rank].update(preds[bi], target[bi], **extra)
                step_value = step_metrics[0].compute()
                all_preds = jnp.concatenate([jnp.atleast_1d(preds[bi]) for bi in batch_indices])
                all_target = jnp.concatenate([jnp.atleast_1d(target[bi]) for bi in batch_indices])
                merged_extra = {
                    k: jnp.concatenate([jnp.atleast_1d(v[bi]) for bi in batch_indices]) for k, v in kwargs_update.items()
                }
                sk_step = sk_metric(all_preds, all_target, **merged_extra)
                _assert_allclose(step_value, sk_step, atol=self.atol)

        # final aggregation must equal the oracle on ALL data; feed the oracle
        # in cross-rank gather order (all of rank 0's batches, then rank 1's,
        # ...) so sample-ordered outputs line up too.
        result = metrics[0].compute()
        gather_order = [i for rank in range(world_size) for i in range(rank, NUM_BATCHES, world_size)]
        all_preds = jnp.concatenate([jnp.atleast_1d(preds[i]) for i in gather_order])
        all_target = jnp.concatenate([jnp.atleast_1d(target[i]) for i in gather_order])
        merged_extra = {k: jnp.concatenate([jnp.atleast_1d(v[i]) for i in gather_order]) for k, v in kwargs_update.items()}
        sk_result = sk_metric(all_preds, all_target, **merged_extra)
        _assert_allclose(result, sk_result, atol=self.atol)

        if ddp:
            # every rank computes the same synced value
            for m in metrics[1:]:
                _assert_allclose(m.compute(), sk_result, atol=self.atol)

        # reset clears state
        metrics[0].reset()
        assert metrics[0]._update_count == 0

    def run_differentiability_test(
        self,
        preds: jnp.ndarray,
        target: jnp.ndarray,
        metric_module: type,
        metric_functional: Callable,
        metric_args: Optional[dict] = None,
    ) -> None:
        """Check the ``is_differentiable`` contract against real gradients.

        JAX analogue of the reference's ``run_differentiability_test``
        (``tests/helpers/testers.py:530-564``, which runs
        ``torch.autograd.gradcheck`` when ``is_differentiable`` and asserts
        ``requires_grad is False`` otherwise): here we take ``jax.grad`` of
        the (sum-reduced) functional form w.r.t. ``preds`` and require

        * gradients always exist and are finite (no NaN from the kernel), and
        * they are somewhere nonzero iff the class declares
          ``is_differentiable=True`` — threshold/argmax/rank-based metrics
          must be locally constant in ``preds``.
        """
        import jax

        metric_args = metric_args or {}
        metric = metric_module(**metric_args)
        assert metric.is_differentiable is not None, (
            f"{metric_module.__name__} must declare is_differentiable"
        )

        p0 = jnp.asarray(preds[0], dtype=jnp.float32)
        t0 = target[0]

        def scalar_fn(p):
            out = metric_functional(p, t0, **metric_args)
            leaves = jax.tree_util.tree_leaves(out)
            tot = jnp.zeros((), dtype=jnp.float32)
            for leaf in leaves:
                tot = tot + jnp.sum(jnp.asarray(leaf, dtype=jnp.float32))
            return tot

        grads = jax.grad(scalar_fn)(p0)
        assert bool(jnp.all(jnp.isfinite(grads))), "non-finite gradient"
        has_grad = bool(jnp.any(grads != 0))
        assert has_grad == bool(metric.is_differentiable), (
            f"{metric_module.__name__}: is_differentiable={metric.is_differentiable} "
            f"but grad nonzero={has_grad}"
        )

    def run_precision_test(
        self,
        preds: jnp.ndarray,
        target: jnp.ndarray,
        metric_module: type,
        metric_functional: Callable,
        metric_args: Optional[dict] = None,
        dtype: Any = jnp.bfloat16,
        atol: float = 1e-2,
        rtol: float = 1e-2,
    ) -> None:
        """Half-precision support check (reference ``testers.py:297-326``).

        Stronger than the reference's run-and-assert-tensor: the class and
        functional forms are fed ``dtype`` (bf16 by default — the TPU native
        half type) inputs and the results must stay finite AND within a loose
        tolerance of the fp32 functional result.
        """
        metric_args = metric_args or {}
        p_half = jnp.asarray(preds[0], dtype=dtype)
        t0 = target[0]
        if jnp.issubdtype(jnp.asarray(t0).dtype, jnp.floating):
            t_half = jnp.asarray(t0, dtype=dtype)
        else:
            t_half = t0

        import jax

        p32 = jnp.asarray(preds[0], jnp.float32)
        fn_ref32 = metric_functional(p32, t0, **metric_args)
        cls_ref32 = metric_module(**metric_args)(p32, t0)

        fn_half = metric_functional(p_half, t_half, **metric_args)
        cls_half = metric_module(**metric_args)(p_half, t_half)

        for res, ref32 in ((fn_half, fn_ref32), (cls_half, cls_ref32)):
            for got, want in zip(jax.tree_util.tree_leaves(res), jax.tree_util.tree_leaves(ref32)):
                got = np.asarray(got, dtype=np.float32)
                assert np.all(np.isfinite(got)), "non-finite half-precision result"
                np.testing.assert_allclose(got, np.asarray(want, np.float32), atol=atol, rtol=rtol)


class DummyListMetric(Metric):
    """Minimal cat-list-state metric for protocol tests."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("x", [], dist_reduce_fx="cat")

    def update(self, x) -> None:
        self.x.append(jnp.asarray(x, dtype=jnp.float32))

    def compute(self):
        return self.x


class DummyMetric(Metric):
    """Minimal metric for protocol tests."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, x) -> None:
        self.x = self.x + jnp.asarray(x, dtype=jnp.float32).sum()

    def compute(self):
        return self.x
