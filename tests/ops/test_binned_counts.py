"""Pallas binning kernel vs numpy oracle (interpret mode on CPU) and the
XLA fallback; plus the bincount fast paths."""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.ops.binned_counts import _binned_counts_pallas, _binned_counts_xla, binned_counts
from metrics_tpu.utilities.data import _bincount


def _oracle(preds, target, thr):
    mask = preds[:, :, None] >= thr[None, None, :]
    tgt = target[:, :, None].astype(bool)
    return (
        (mask & tgt).sum(0).astype(np.float32),
        (mask & ~tgt).sum(0).astype(np.float32),
        (~mask & tgt).sum(0).astype(np.float32),
    )


@pytest.mark.parametrize("n,c,t", [(100, 1, 5), (1000, 3, 100), (8192, 2, 7)])
def test_xla_matches_oracle(n, c, t):
    rng = np.random.default_rng(0)
    preds = rng.uniform(0, 1, (n, c)).astype(np.float32)
    target = (rng.uniform(0, 1, (n, c)) > 0.7).astype(np.int32)
    thr = np.linspace(0, 1.0, t).astype(np.float32)
    got = _binned_counts_xla(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(thr))
    want = _oracle(preds, target, thr)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), w, atol=0.5)


@pytest.mark.parametrize("n,c,t", [(100, 1, 5), (10000, 3, 33)])
def test_pallas_interpret_matches_oracle(n, c, t):
    rng = np.random.default_rng(1)
    preds = rng.uniform(0, 1, (n, c)).astype(np.float32)
    target = (rng.uniform(0, 1, (n, c)) > 0.7).astype(np.int32)
    thr = np.linspace(0, 1.0, t).astype(np.float32)
    got = _binned_counts_pallas(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(thr), interpret=True)
    want = _oracle(preds, target, thr)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), w, atol=0.5)


def test_dispatch_runs():
    preds = jnp.asarray([[0.1], [0.6], [0.9]])
    target = jnp.asarray([[0], [1], [1]])
    thr = jnp.asarray([0.0, 0.5, 1.0])
    tps, fps, fns = binned_counts(preds, target, thr)
    np.testing.assert_allclose(np.asarray(tps), [[2.0, 2.0, 0.0]], atol=0.5)
    np.testing.assert_allclose(np.asarray(fps), [[1.0, 0.0, 0.0]], atol=0.5)
    np.testing.assert_allclose(np.asarray(fns), [[0.0, 0.0, 2.0]], atol=0.5)


@pytest.mark.parametrize("minlength", [5, 100, 5000])
def test_bincount_paths(minlength):
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.integers(0, minlength, 10000))
    got = np.asarray(_bincount(x, minlength))
    want = np.bincount(np.asarray(x), minlength=minlength)
    np.testing.assert_array_equal(got, want)


def test_confmat_large_c_matmul_path():
    from metrics_tpu.functional import confusion_matrix

    rng = np.random.default_rng(3)
    c = 100  # > 64 -> MXU dot path
    preds = jnp.asarray(rng.integers(0, c, 5000))
    target = jnp.asarray(rng.integers(0, c, 5000))
    got = np.asarray(confusion_matrix(preds, target, num_classes=c))
    want = np.zeros((c, c), dtype=np.int64)
    np.fill_diagonal(want, 0)
    for t, p in zip(np.asarray(target), np.asarray(preds)):
        want[t, p] += 1
    np.testing.assert_array_equal(got, want)
