"""Pallas argmax-compare kernel vs the jnp.argmax oracle (interpret mode on
CPU) and the XLA fallback, pinning the first-max tie and NaN-greatest
contracts."""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.ops.argmax_compare import (
    _argmax_correct_pallas,
    _argmax_correct_xla,
    argmax_correct_count,
)


def _oracle(preds, target):
    return int((np.argmax(preds, axis=1) == target).sum())


@pytest.mark.parametrize("n,c", [(7, 2), (100, 10), (5000, 10), (2048, 3), (2049, 17)])
def test_pallas_interpret_matches_oracle(n, c):
    rng = np.random.default_rng(0)
    preds = rng.normal(size=(n, c)).astype(np.float32)
    target = rng.integers(0, c, n).astype(np.int32)
    got = _argmax_correct_pallas(jnp.asarray(preds), jnp.asarray(target), interpret=True)
    assert int(got) == _oracle(preds, target)


def test_pallas_tie_first_index():
    # ties take the FIRST max index, exactly like jnp.argmax
    preds = np.asarray(
        [[1.0, 1.0, 0.0], [0.5, 0.7, 0.7], [2.0, 2.0, 2.0]], dtype=np.float32
    )
    target = np.asarray([0, 1, 2], dtype=np.int32)  # matches: row0 yes, row1 yes, row2 no
    got = _argmax_correct_pallas(jnp.asarray(preds), jnp.asarray(target), interpret=True)
    assert int(got) == _oracle(preds, target) == 2


def test_pallas_nan_sorts_greatest():
    preds = np.asarray(
        [
            [0.0, np.nan, 5.0],  # argmax -> 1 (first NaN)
            [np.nan, np.nan, 0.0],  # argmax -> 0
            [1.0, 0.0, 2.0],  # argmax -> 2
        ],
        dtype=np.float32,
    )
    target = np.asarray([1, 0, 2], dtype=np.int32)
    got = _argmax_correct_pallas(jnp.asarray(preds), jnp.asarray(target), interpret=True)
    assert int(got) == _oracle(preds, target) == 3


def test_pallas_bf16_inputs():
    rng = np.random.default_rng(1)
    preds = jnp.asarray(rng.normal(size=(300, 10)), dtype=jnp.bfloat16)
    target = jnp.asarray(rng.integers(0, 10, 300).astype(np.int32))
    got = _argmax_correct_pallas(preds, target, interpret=True)
    want = int(jnp.sum(jnp.argmax(preds, axis=1) == target))
    assert int(got) == want


def test_xla_and_dispatch():
    rng = np.random.default_rng(2)
    preds = rng.normal(size=(999, 5)).astype(np.float32)
    target = rng.integers(0, 5, 999).astype(np.int32)
    want = _oracle(preds, target)
    assert int(_argmax_correct_xla(jnp.asarray(preds), jnp.asarray(target))) == want
    assert int(argmax_correct_count(jnp.asarray(preds), jnp.asarray(target))) == want


def test_stat_scores_fast_path_unchanged():
    """The micro-multiclass fast path still equals the full formulation."""
    from metrics_tpu.functional.classification.stat_scores import _stat_scores_update

    rng = np.random.default_rng(3)
    preds = jnp.asarray(rng.normal(size=(257, 10)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 10, 257))
    fast = _stat_scores_update(preds, target, reduce="micro", validate_args=False)
    slow = _stat_scores_update(preds, target, reduce="micro", validate_args=True)
    for f, s in zip(fast, slow):
        assert int(f) == int(s)


def test_empty_input_returns_zero():
    got = argmax_correct_count(jnp.zeros((0, 5)), jnp.zeros((0,), jnp.int32))
    assert int(got) == 0
