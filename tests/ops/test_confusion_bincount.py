"""Pallas confusion-matrix / bincount tiles vs numpy oracle (interpret mode
on CPU) and the XLA fallbacks; plus the wiring into ``_bincount`` and
``_confusion_matrix_update``."""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu.ops.confusion_bincount import (
    _bincount_pallas,
    _confusion_pallas,
    _confusion_xla,
    bincount_counts,
    confusion_counts,
)


def _oracle_confusion(preds, target, c):
    out = np.zeros((c, c), np.int64)
    for p, t in zip(preds, target):
        if 0 <= p < c and 0 <= t < c:
            out[t, p] += 1
    return out


@pytest.mark.parametrize("n,c", [(16, 3), (1000, 10), (4096, 128), (2048, 2)])
def test_confusion_xla_matches_oracle(n, c):
    rng = np.random.default_rng(0)
    preds = rng.integers(0, c, n).astype(np.int32)
    target = rng.integers(0, c, n).astype(np.int32)
    got = _confusion_xla(jnp.asarray(preds), jnp.asarray(target), c)
    np.testing.assert_array_equal(np.asarray(got), _oracle_confusion(preds, target, c))


@pytest.mark.parametrize("n,c", [(16, 3), (1000, 10), (5000, 64), (2048, 128)])
def test_confusion_pallas_interpret_matches_oracle(n, c):
    rng = np.random.default_rng(1)
    preds = rng.integers(0, c, n).astype(np.int32)
    target = rng.integers(0, c, n).astype(np.int32)
    got = _confusion_pallas(jnp.asarray(preds), jnp.asarray(target), c, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), _oracle_confusion(preds, target, c))


def test_confusion_out_of_range_dropped():
    """Out-of-range ids (incl. the -1 padding sentinel) contribute nothing."""
    preds = np.asarray([0, 1, -1, 5, 2], np.int32)
    target = np.asarray([0, -1, 1, 1, 7], np.int32)
    want = _oracle_confusion(preds, target, 3)  # only the (0, 0) pair lands
    got_xla = _confusion_xla(jnp.asarray(preds), jnp.asarray(target), 3)
    got_pl = _confusion_pallas(jnp.asarray(preds), jnp.asarray(target), 3, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_xla), want)
    np.testing.assert_array_equal(np.asarray(got_pl), want)


def test_confusion_non_block_multiple():
    """Sample counts that are not a block multiple pad without contributing."""
    rng = np.random.default_rng(2)
    n, c = 2048 + 37, 7
    preds = rng.integers(0, c, n).astype(np.int32)
    target = rng.integers(0, c, n).astype(np.int32)
    got = _confusion_pallas(jnp.asarray(preds), jnp.asarray(target), c, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), _oracle_confusion(preds, target, c))


@pytest.mark.parametrize("n,m", [(10, 4), (1000, 100), (5000, 513), (2048, 2048)])
def test_bincount_pallas_interpret_matches_numpy(n, m):
    rng = np.random.default_rng(3)
    x = rng.integers(0, m, n).astype(np.int32)
    got = _bincount_pallas(jnp.asarray(x), m, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.bincount(x, minlength=m))


def test_bincount_counts_cpu_fallback_matches_numpy():
    rng = np.random.default_rng(4)
    x = rng.integers(0, 50, 777).astype(np.int32)
    got = bincount_counts(jnp.asarray(x), 50)
    np.testing.assert_array_equal(np.asarray(got), np.bincount(x, minlength=50))


def test_confusion_counts_cpu_fallback_matches_oracle():
    rng = np.random.default_rng(5)
    preds = rng.integers(0, 9, 333).astype(np.int32)
    target = rng.integers(0, 9, 333).astype(np.int32)
    got = confusion_counts(jnp.asarray(preds), jnp.asarray(target), 9)
    np.testing.assert_array_equal(np.asarray(got), _oracle_confusion(preds, target, 9))


def test_confusion_matrix_metric_unchanged():
    """The metric-level confusion matrix keeps its exact counts through the
    rewired update (CPU: bincount path below 64 classes, chunk-scanned MXU
    contraction above)."""
    from metrics_tpu import ConfusionMatrix

    rng = np.random.default_rng(6)
    for c in (5, 80):
        preds = jnp.asarray(rng.normal(size=(500, c)).astype(np.float32))
        target = jnp.asarray(rng.integers(0, c, 500))
        m = ConfusionMatrix(num_classes=c)
        m.update(preds, target)
        want = _oracle_confusion(np.asarray(preds).argmax(1), np.asarray(target), c)
        np.testing.assert_array_equal(np.asarray(m.compute()), want)
