"""CI smoke: jitted ``make_step`` + ``make_epoch`` with observability ON.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.obs_smoke`` (the CI
tier-1 job does). Asserts that the enabled obs layer does not break tracing
or change values, that counters/annotations actually record, and that the
export surface produces output — the cheap end-to-end arm of the pinned
unit tests in ``tests/bases/test_obs.py``.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main() -> None:
    import metrics_tpu.obs as obs
    from metrics_tpu import Accuracy
    from metrics_tpu.steps import make_epoch, make_step

    obs.enable()
    obs.install_compile_listener()

    # jitted step: two shapes -> two tracings, values unchanged
    init, step, compute = make_step(Accuracy, num_classes=3)
    jstep = jax.jit(step)
    state = init()
    state, v1 = jstep(state, jnp.asarray([0, 1, 2, 2]), jnp.asarray([0, 1, 1, 2]))
    state, v2 = jstep(state, jnp.asarray([1, 1, 0, 2, 0, 1]), jnp.asarray([0, 1, 0, 2, 0, 1]))
    assert float(v1) == 0.75, float(v1)
    assert abs(float(compute(state)) - 0.8) < 1e-6, float(compute(state))
    assert obs.get_counter("step.traces", step="Accuracy.step") == 2

    # named scopes in the compiled program (compile fresh: the persistent
    # cache strips op metadata from its key, so a scope-free executable
    # cached by a disabled-mode run would otherwise be served here)
    try:
        jax.config.update("jax_enable_compilation_cache", False)
        hlo = jax.jit(step).lower(init(), jnp.asarray([0, 1]), jnp.asarray([0, 1])).compile().as_text()
    finally:
        jax.config.update("jax_enable_compilation_cache", True)
    assert "Accuracy.step" in hlo, "named scope missing from compiled HLO"

    # fused epoch: compile/run split + launch accounting
    initE, epoch, computeE = make_epoch(Accuracy, num_classes=3)
    preds = jnp.asarray([[0, 1], [2, 1]])
    target = jnp.asarray([[0, 1], [2, 0]])
    st, _ = epoch(initE(), preds, target)
    st, _ = epoch(st, preds, target)
    assert float(computeE(st)) == 0.75
    assert obs.get_counter("compiles", step="Accuracy.epoch") == 1
    assert obs.get_counter("runs", step="Accuracy.epoch") == 1
    assert obs.get_counter("epoch.batches_folded", step="Accuracy.epoch") == 4

    # export surface produces output
    snap = obs.snapshot()
    assert snap["counters"], "empty counter snapshot"
    text = obs.to_prometheus(snap)
    assert "metrics_tpu_step_traces" in text, text[:200]

    # performance tier: device-timing histograms + cost-analysis gauges on
    # a fresh fused-epoch factory (opt-in modes; first launch pays compile
    # and records the cost gauges, second is a timed cache hit)
    obs.configure(device_timing=True, cost_analysis=True)
    initT, epochT, computeT = make_epoch(Accuracy, num_classes=3)
    stT, _ = epochT(initT(), preds, target)
    stT, _ = epochT(stT, preds, target)
    assert float(computeT(stT)) == 0.75
    hist = obs.get_histogram("step.latency_ms", step="Accuracy.epoch")
    assert hist is not None and hist.count == 1 and hist.p50 > 0, hist
    assert obs.get_gauge("step.bytes_accessed", step="Accuracy.epoch") > 0
    assert obs.get_gauge("step.flops", step="Accuracy.epoch") is not None
    text = obs.to_prometheus()
    assert "# TYPE metrics_tpu_step_latency_ms histogram" in text
    assert 'metrics_tpu_step_latency_ms_bucket{step="Accuracy.epoch",le="+Inf"} 1' in text
    assert "metrics_tpu_step_latency_ms_sum" in text
    obs.configure(device_timing=False, cost_analysis=False)

    # programmatic profile capture writes trace files
    import tempfile

    logdir = tempfile.mkdtemp(prefix="obs_smoke_prof.")
    with obs.profile(logdir):
        st2, _ = epoch(st, preds, target)
        jax.block_until_ready(st2)
    trace_files = [n for _, _, fs in os.walk(logdir) for n in fs]
    assert trace_files, "profile capture produced no trace files"
    assert obs.get_counter("profile.captures") == 1

    # fleet health: this healthy single-host run must classify healthy, and
    # a planted straggler gauge must flip it
    report = obs.HealthMonitor(warn=False).check()
    assert report["healthy"], report
    obs.set_gauge("sync.arrival_skew_ms", 10_000.0)
    report = obs.HealthMonitor(warn=False).check()
    assert [w["kind"] for w in report["warnings"]] == ["straggler"], report

    print("obs smoke OK:", len(snap["counters"]), "counter series,",
          f"{obs.get_counter('jax.compile_seconds'):.2f}s backend compile time,",
          f"epoch p50 {hist.p50 * 1000:.0f}us,",
          f"{len(trace_files)} profile trace file(s)")


if __name__ == "__main__":
    main()
