"""CI smoke: jitted ``make_step`` + ``make_epoch`` with observability ON.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.obs_smoke`` (the CI
tier-1 job does). Asserts that the enabled obs layer does not break tracing
or change values, that counters/annotations actually record, and that the
export surface produces output — the cheap end-to-end arm of the pinned
unit tests in ``tests/bases/test_obs.py``.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main() -> None:
    import metrics_tpu.obs as obs
    from metrics_tpu import Accuracy
    from metrics_tpu.steps import make_epoch, make_step

    obs.enable()
    obs.install_compile_listener()

    # jitted step: two shapes -> two tracings, values unchanged
    init, step, compute = make_step(Accuracy, num_classes=3)
    jstep = jax.jit(step)
    state = init()
    state, v1 = jstep(state, jnp.asarray([0, 1, 2, 2]), jnp.asarray([0, 1, 1, 2]))
    state, v2 = jstep(state, jnp.asarray([1, 1, 0, 2, 0, 1]), jnp.asarray([0, 1, 0, 2, 0, 1]))
    assert float(v1) == 0.75, float(v1)
    assert abs(float(compute(state)) - 0.8) < 1e-6, float(compute(state))
    assert obs.get_counter("step.traces", step="Accuracy.step") == 2

    # named scopes in the compiled program
    hlo = jax.jit(step).lower(init(), jnp.asarray([0, 1]), jnp.asarray([0, 1])).compile().as_text()
    assert "Accuracy.step" in hlo, "named scope missing from compiled HLO"

    # fused epoch: compile/run split + launch accounting
    initE, epoch, computeE = make_epoch(Accuracy, num_classes=3)
    preds = jnp.asarray([[0, 1], [2, 1]])
    target = jnp.asarray([[0, 1], [2, 0]])
    st, _ = epoch(initE(), preds, target)
    st, _ = epoch(st, preds, target)
    assert float(computeE(st)) == 0.75
    assert obs.get_counter("compiles", step="Accuracy.epoch") == 1
    assert obs.get_counter("runs", step="Accuracy.epoch") == 1
    assert obs.get_counter("epoch.batches_folded", step="Accuracy.epoch") == 4

    # export surface produces output
    snap = obs.snapshot()
    assert snap["counters"], "empty counter snapshot"
    text = obs.to_prometheus(snap)
    assert "metrics_tpu_step_traces" in text, text[:200]
    print("obs smoke OK:", len(snap["counters"]), "counter series,",
          f"{obs.get_counter('jax.compile_seconds'):.2f}s backend compile time")


if __name__ == "__main__":
    main()
