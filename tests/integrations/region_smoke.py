"""CI smoke: multi-region serving survives partition and failover, bitwise.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.region_smoke``
(the CI step does, mirroring ``elastic_smoke``). One 3-region
:class:`~metrics_tpu.serve.RegionalMesh` (each region an in-region
aggregation tree), clients delivering under a seeded 10%
:class:`~metrics_tpu.ft.faults.WireChaos` schedule, driven through the
two failure arcs the multi-region tier exists for:

* **partition + heal** — one region is DCN-partitioned from the mesh
  (:func:`~metrics_tpu.ft.faults.region_partition`) while every region
  keeps ingesting its own clients; during the partition each side answers
  ``/query`` with local-complete / global-stale values (per-region
  freshness + ``degraded`` verdict; the ``stale_reads="reject"`` policy
  answers 503 over HTTP), and on heal the next cumulative cross-ship
  repairs every region's global view **bitwise** — no anti-entropy pass.
* **kill + generation-fenced promotion** — a region's root is hard-killed
  (:func:`~metrics_tpu.ft.faults.kill_region`; peers' replication sweeps
  fail → ``partition_detected``), then a warm standby is promoted
  (:func:`~metrics_tpu.ft.faults.promote_region`): checkpoint restore +
  engine-store warmup with **zero backend compiles** asserted under the
  jax.monitoring compile listener, the successor generation minted and
  fenced at every peer — a captured pre-kill ZOMBIE ship is refused
  loudly (``serve.fenced_ships``, HTTP 409 family) and never merged.

Acceptance: after BOTH arcs, every region's global ``/query`` is
bitwise-equal to the flat oracle merge of exactly the accepted snapshots,
every injected fault is visible in obs counters, and the armed
:class:`~metrics_tpu.obs.health.HealthMonitor` conditions
(``peer_stale`` / ``partition_detected`` / ``fenced_zombie``) all fired.
"""
import json
import os
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

SEED = 20260805
N_CLIENTS = 30
N_INTERVALS = 3
SAMPLES = 64
TENANT = "region"
REGIONS = ("r0", "r1", "r2")


def _factory():
    from metrics_tpu import MaxMetric, SumMetric
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.streaming import StreamingAUROC

    return MetricCollection(
        {"auroc": StreamingAUROC(num_bins=128), "seen": SumMetric(), "peak": MaxMetric()}
    )


def _client_snapshots():
    import numpy as np

    import jax.numpy as jnp

    from metrics_tpu.serve.wire import encode_state

    out = {}
    for c in range(N_CLIENTS):
        cid = f"client-{c:03d}"
        rng = np.random.default_rng(9000 + c)
        coll = _factory()
        blobs = []
        for interval in range(N_INTERVALS):
            preds = jnp.asarray(rng.uniform(0, 1, SAMPLES).astype(np.float32))
            target = jnp.asarray(
                (rng.uniform(0, 1, SAMPLES) < 0.3 + 0.4 * np.asarray(preds)).astype(np.int32)
            )
            coll["auroc"].update(preds, target)
            coll["seen"].update(jnp.asarray(float(SAMPLES)))
            coll["peak"].update(preds)
            blobs.append(encode_state(coll, tenant=TENANT, client_id=cid, watermark=(0, interval)))
        out[cid] = blobs
    return out


def main() -> None:
    import tempfile

    import numpy as np

    from metrics_tpu import engine as eng
    from metrics_tpu import obs
    from metrics_tpu.ft import faults
    from metrics_tpu.ft.retry import RetryPolicy
    from metrics_tpu.obs.health import HealthMonitor
    from metrics_tpu.obs.registry import get_counter
    from metrics_tpu.serve import (
        Aggregator,
        FencedGenerationError,
        MetricsServer,
        Region,
        RegionalMesh,
    )
    from metrics_tpu.serve.wire import WireFormatError, peek_header

    obs.reset()
    obs.enable()
    assert obs.install_compile_listener(), "compile listener unavailable — cannot assert"
    root = tempfile.mkdtemp(prefix="region_smoke_")
    store = eng.ProgramStore(os.path.join(root, "store"))
    tenants = {TENANT: _factory}
    mesh = RegionalMesh(
        [
            Region(
                name,
                tenants,
                fan_out=(2,),
                checkpoint_dir=os.path.join(root, name),
                engine=eng.AotEngine(store),
            )
            for name in REGIONS
        ],
        retry_policy=RetryPolicy(
            max_retries=1, backoff_s=0.01, max_backoff_s=0.05, deadline_s=0.25,
            jitter="decorrelated", jitter_seed=SEED,
        ),
    )
    snapshots = _client_snapshots()
    home = {cid: REGIONS[i % len(REGIONS)] for i, cid in enumerate(sorted(snapshots))}
    chaos = faults.WireChaos(
        SEED, p_drop=0.025, p_duplicate=0.025, p_reorder=0.025, p_corrupt=0.025, p_delay=0.0
    )
    delivered = set()  # (client_id, interval) delivered uncorrupted + admitted

    def deliver(blobs) -> None:
        for blob in blobs:
            try:
                _, header = peek_header(blob)
            except WireFormatError:
                continue  # framing mangled: nothing to route, refused anywhere
            cid = str(header["client"])
            try:
                mesh.region(home[cid]).ingest(blob, client_id=cid)
            except WireFormatError:
                pass  # corrupt-in-flight: refused by the crc32
            else:
                delivered.add((cid, int(header["watermark"][1])))

    def deliver_interval(interval: int, chaotic: bool = True) -> None:
        for cid in sorted(snapshots):
            if chaotic:
                _, now_blobs = chaos.plan(snapshots[cid][interval])
                deliver(now_blobs)
            else:
                deliver([snapshots[cid][interval]])
        if chaotic:
            deliver(chaos.end_round())
        for name in REGIONS:
            mesh.region(name).pump()

    monitor = HealthMonitor(
        warn=False,
        name="region",
        peer_staleness_ms=50.0,
        partition_detected=True,
        fenced_zombie=True,
    )

    # ---- arc 1: partition r2, keep ingesting everywhere, heal -----------
    with faults.region_partition(mesh, "r2"):
        deliver_interval(0)
        mesh.replicate()
        time.sleep(0.08)  # let the partitioned peer's replica age past 50ms
        q_healthy = mesh.region("r0").query_global(TENANT)
        assert q_healthy["local_complete"] is True
        assert "r2" in q_healthy["stale_regions"], q_healthy["regions"]
        assert q_healthy["degraded"] is True
        q_isolated = mesh.region("r2").query_global(TENANT)
        assert set(q_isolated["stale_regions"]) == {"r0", "r1"}, q_isolated["regions"]
        report = monitor.check()
        fired = {w["kind"] for w in report["warnings"]}
        assert "peer_stale" in fired, report
        # the degraded-read REJECT policy over HTTP: 503 naming the region
        r0 = mesh.region("r0")
        r0.stale_reads, r0.max_staleness_s = "reject", 0.01
        server = MetricsServer(r0.global_view, region=r0, port=0).start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            try:
                urllib.request.urlopen(f"{base}/query?tenant={TENANT}&scope=global", timeout=10)
                raise AssertionError("stale global query must answer 503 under reject policy")
            except urllib.error.HTTPError as err:
                assert err.code == 503, err.code
                body = json.loads(err.read().decode())
                assert "r2" in body["stale_regions"], body
            r0.stale_reads, r0.max_staleness_s = "degraded", None
            q_http = json.load(
                urllib.request.urlopen(f"{base}/query?tenant={TENANT}&scope=global", timeout=10)
            )
            assert q_http["degraded"] is True and "r2" in q_http["stale_regions"]
        finally:
            server.stop()
    assert obs.get_counter("chaos.injected", kind="region_partition") >= 1

    # ---- heal: the next cumulative cross-ship repairs bitwise -----------
    deliver_interval(1)
    mesh.replicate()
    q_healed = mesh.region("r0").query_global(TENANT)
    assert q_healed["degraded"] is False, q_healed["regions"]

    # ---- arc 2: kill r1's root, promote under fencing -------------------
    for name in REGIONS:
        mesh.region(name).save()
    zombie_blobs = mesh.region("r1").snapshot_payloads()  # the would-be zombie's ships
    faults.kill_region(mesh, "r1")
    mesh.replicate()  # sweeps to the dead region fail -> partition_detected
    report = monitor.check()
    fired = {w["kind"] for w in report["warnings"]}
    assert "partition_detected" in fired, report
    assert obs.sum_counter("serve.replication_errors") >= 1

    # warm standby promotion: checkpoint restore + engine-store warmup, and
    # the promoted tier's ENTIRE first round (replicate + folds + queries)
    # must perform ZERO backend compiles — the PR 11 cold-start contract
    eng.reset_memory_cache()
    compiles_before = get_counter("jax.compiles")
    promoted = faults.promote_region(mesh, "r1")
    assert promoted.generation >= 1
    deliver_interval(2, chaotic=False)  # clients keep shipping; cumulative repairs
    mesh.replicate()
    for name in REGIONS:
        mesh.region(name).query_global(TENANT)
    compiled = get_counter("jax.compiles") - compiles_before
    assert compiled == 0, (
        f"promotion + first post-failover round performed {compiled} backend"
        " compile(s) — warm standby promotion must be compile-free"
    )

    # the zombie pre-failover root's ships are refused loudly, never merged
    fenced = 0
    for blob in zombie_blobs:
        try:
            mesh.region("r0").accept_replica(blob)
        except FencedGenerationError:
            fenced += 1
    assert fenced == len(zombie_blobs), "every zombie ship must be fence-refused"
    assert obs.sum_counter("serve.fenced_ships") >= fenced
    report = monitor.check()
    assert "fenced_zombie" in {w["kind"] for w in report["warnings"]}, report
    mesh.replicate()

    # ---- oracle: flat merge of exactly the accepted snapshots -----------
    # interval 2 was delivered clean everywhere, so per client the highest
    # accepted watermark is 2; earlier chaos fates are superseded by the
    # cumulative contract (and nothing pre-checkpoint was lost: the
    # promoted standby restored its regional slots and the clients'
    # interval-2 re-ships repaired the tail)
    accepted = {}
    for cid, interval in delivered:
        if cid not in accepted or interval > accepted[cid]:
            accepted[cid] = interval
    assert all(i == N_INTERVALS - 1 for i in accepted.values())
    flat = Aggregator("flat-oracle")
    flat.register_tenant(TENANT, _factory)
    for cid, interval in sorted(accepted.items()):
        flat.ingest(snapshots[cid][interval])
    flat.flush()
    flat_tenant = flat._tenant(TENANT)
    if flat_tenant.merged_leaves is None:
        flat_tenant.fold()
    for name in REGIONS:
        region = mesh.region(name)
        region.query_global(TENANT)  # self-ship + fold so the view is current
        gt = region.global_view._tenant(TENANT)
        assert gt.spec == flat_tenant.spec
        for (path, _), ours, oracle in zip(gt.spec, gt.merged_leaves, flat_tenant.merged_leaves):
            assert np.array_equal(np.asarray(ours), np.asarray(oracle)), (
                f"region {name} global leaf {'/'.join(path)} differs from the"
                " accepted-snapshot oracle after partition+heal and kill+promote"
            )

    # ---- every injected fault is visible in obs -------------------------
    assert obs.get_counter("chaos.injected", kind="region_kill") == 1
    assert obs.get_counter("chaos.injected", kind="promote") == 1
    assert obs.get_counter("serve.promotions", region="r1") == 1
    for kind, count in chaos.counts.items():
        if kind in ("deliver", "reorder") or count == 0:
            continue
        assert obs.get_counter("chaos.injected", kind=kind) == count, kind
    assert obs.sum_counter("serve.cross_region_merges") > 0

    faults_injected = sum(v for k, v in chaos.counts.items() if k != "deliver")
    print(
        f"region smoke: {N_CLIENTS} clients x {N_INTERVALS} intervals across"
        f" {len(REGIONS)} regions at 10% wire faults ({faults_injected} injected)"
        f" through partition(r2)+heal and kill(r1)+promote(gen {promoted.generation},"
        f" {fenced} zombie ships fenced, zero backend compiles) — every region's"
        " global /query bitwise-equal to the accepted-snapshot oracle",
        flush=True,
    )
    print("region smoke OK", flush=True)


if __name__ == "__main__":
    main()
