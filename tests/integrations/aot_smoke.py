"""CI smoke: warm node revival with ZERO backend compiles, bitwise /query.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.aot_smoke`` (the
CI test job does, mirroring ``serve_smoke``). The cold-start-elimination
acceptance with a REAL process boundary:

* the parent builds an AOT-armed :class:`~metrics_tpu.serve.Aggregator`
  (persistent :class:`~metrics_tpu.engine.ProgramStore` + checkpoint
  dir), folds payloads from 5 clients, records the ``/query`` answer over
  HTTP plus the merged state leaves byte-for-byte, and checkpoints —
  the warmup manifest (program keys + shapes) rides the manifest;
* a FRESH python process (no shared jit caches, no shared engine memory)
  re-registers the tenants, ``warmup()``s off the checkpoint manifest —
  executables deserialize from the store — then ``restore()``s and runs
  its FIRST FOLD under the jax.monitoring compile listener: the listener
  must record **zero backend compiles** (the whole point of the engine
  subsystem), the warm fold must also be >= 10x faster than the parent's
  measured cold fold, and the HTTP ``/query`` answer must be BITWISE
  equal to the pre-kill oracle (state leaves compared as raw bytes, the
  JSON values exactly).
"""
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_CLIENTS = 5
SAMPLES = 128
TENANT = "aot"


def _factory():
    from metrics_tpu import MaxMetric, SumMetric
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.streaming import StreamingAUROC

    return MetricCollection(
        {"auroc": StreamingAUROC(num_bins=128), "seen": SumMetric(), "peak": MaxMetric()}
    )


def _payloads():
    import numpy as np

    import jax.numpy as jnp

    from metrics_tpu.serve.wire import encode_state

    out = []
    for c in range(N_CLIENTS):
        rng = np.random.default_rng(100 + c)
        coll = _factory()
        preds = jnp.asarray(rng.uniform(0, 1, SAMPLES).astype(np.float32))
        target = jnp.asarray(
            (rng.uniform(0, 1, SAMPLES) < 0.3 + 0.4 * np.asarray(preds)).astype(np.int32)
        )
        coll["auroc"].update(preds, target)
        coll["seen"].update(jnp.asarray(float(SAMPLES)))
        coll["peak"].update(preds)
        out.append(encode_state(coll, tenant=TENANT, client_id=f"client-{c}", watermark=(0, 0)))
    return out


def _build_aggregator(root: str):
    from metrics_tpu import engine as eng
    from metrics_tpu.serve.aggregator import Aggregator

    store = eng.ProgramStore(os.path.join(root, "store"))
    return Aggregator(
        "root",
        checkpoint_dir=os.path.join(root, "ckpt"),
        engine=eng.AotEngine(store),
        prewarm_buckets=(),
    )


def _http_query(agg) -> dict:
    from metrics_tpu.serve.endpoints import MetricsServer

    server = MetricsServer(agg, port=0).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/query?tenant={TENANT}", timeout=10
        ) as resp:
            return json.loads(resp.read().decode())
    finally:
        server.stop()


def _leaf_hexes(agg) -> list:
    import numpy as np

    tenant = agg._tenants[TENANT]
    return [np.asarray(leaf).tobytes().hex() for leaf in tenant.merged_leaves]


def parent(root: str) -> None:
    agg = _build_aggregator(root)
    agg.register_tenant(TENANT, _factory)
    for blob in _payloads():
        assert agg.ingest(blob)
    # cold first fold: measured for the >=10x acceptance the warm child
    # must beat (trace + lower + backend compile + execute)
    t0 = time.perf_counter()
    agg.flush()
    cold_ms = (time.perf_counter() - t0) * 1000.0
    oracle = _http_query(agg)
    assert oracle["clients"] == N_CLIENTS, oracle
    agg.save()
    manifest = agg._manager.read_manifest()
    warm_meta = manifest["extra"]["serve"]["warmup"]
    assert warm_meta["tenants"][TENANT], "warmup manifest must record fold buckets"
    assert warm_meta["environment"]["jax_version"], "warmup manifest must record the environment"
    with open(os.path.join(root, "oracle.json"), "w") as f:
        json.dump({"query": oracle, "leaves": _leaf_hexes(agg), "cold_ms": cold_ms}, f)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tests.integrations.aot_smoke", "--revive", root],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        timeout=600,
    )
    assert proc.returncode == 0, f"revive process failed with {proc.returncode}"
    print(f"aot_smoke OK: cold first fold {cold_ms:.1f}ms; warm revival verified in a fresh process")


def revive(root: str) -> None:
    """The fresh process: warmup + restore, then the assertions."""
    from metrics_tpu import obs
    from metrics_tpu.obs.registry import get_counter

    assert obs.install_compile_listener(), "compile listener unavailable — cannot assert"
    with open(os.path.join(root, "oracle.json")) as f:
        oracle = json.load(f)

    agg = _build_aggregator(root)
    agg.register_tenant(TENANT, _factory)
    compiles_before = get_counter("jax.compiles")
    warmed = agg.warmup()
    assert warmed >= 1, "warmup resolved no programs"
    assert get_counter("jax.compiles") == compiles_before, (
        "warmup paid backend compiles — the program store did not serve the"
        " executables (stale keys? corrupted store?)"
    )
    agg.restore()
    tenant = agg._tenants[TENANT]
    compiles_before = get_counter("jax.compiles")
    t0 = time.perf_counter()
    folded = tenant.fold()
    warm_ms = (time.perf_counter() - t0) * 1000.0
    compiled = get_counter("jax.compiles") - compiles_before
    assert folded == N_CLIENTS, f"first fold saw {folded} clients, wanted {N_CLIENTS}"
    assert compiled == 0, (
        f"the revived node's FIRST FOLD performed {compiled} backend"
        " compile(s) — warm revival must be compile-free"
    )
    assert warm_ms * 10.0 <= oracle["cold_ms"], (
        f"warm first fold {warm_ms:.2f}ms is not >=10x faster than the cold"
        f" {oracle['cold_ms']:.2f}ms"
    )
    # bitwise: merged state leaves as raw bytes, and the HTTP /query JSON
    assert _leaf_hexes(agg) == oracle["leaves"], "merged leaves differ from the pre-kill oracle"
    query = _http_query(agg)
    assert query == oracle["query"], (
        f"/query diverged from the pre-kill oracle:\n{query}\nvs\n{oracle['query']}"
    )
    hits = get_counter("compile.cache_hits", step="serve.fold_stacked", tier="disk")
    assert hits >= 1, "warm start left no disk-tier cache-hit telemetry"
    print(
        f"revive OK: {warmed} programs warmed, first fold {warm_ms:.2f}ms"
        f" (cold was {oracle['cold_ms']:.2f}ms), zero backend compiles,"
        " /query bitwise"
    )


def main() -> int:
    if len(sys.argv) >= 3 and sys.argv[1] == "--revive":
        revive(sys.argv[2])
        return 0
    with tempfile.TemporaryDirectory(prefix="aot_smoke.") as root:
        parent(root)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
