"""Real multi-process DCN sync: 2 jax.distributed processes on localhost.

The analogue of the reference's gloo-pool DDP tests
(``tests/bases/test_ddp.py`` via ``torch.multiprocessing`` spawn): two OS
processes join a JAX coordinator, each accumulates a disjoint data shard,
and ``compute()`` must equal the single-process result on the concatenated
data — exercising the actual ``multihost_utils.process_allgather`` path of
``gather_all_tensors`` (incl. uneven shard sizes), not the in-process
virtual harness."""
import os
import socket
import subprocess
import sys
import textwrap
import time

import pytest

_WORKER = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address={coord!r},
        num_processes=2,
        process_id=int(sys.argv[1]),
    )
    print(f"rank {{int(sys.argv[1])}} init", flush=True)
    import jax.numpy as jnp
    import numpy as np

    from metrics_tpu import Accuracy, AUROC

    rank = jax.process_index()
    rng = np.random.default_rng(0)
    preds = rng.uniform(0, 1, 200)
    target = rng.integers(0, 2, 200)
    # uneven shards: rank 0 gets 120 samples, rank 1 gets 80
    lo, hi = (0, 120) if rank == 0 else (120, 200)

    acc = Accuracy()
    acc.update(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))
    total = float(acc.compute())
    ref = ((preds >= 0.5).astype(int) == target).mean()
    np.testing.assert_allclose(total, ref, atol=1e-6)

    auroc = AUROC()   # cat-list state -> uneven all-gather path
    auroc.update(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))
    from sklearn.metrics import roc_auc_score
    np.testing.assert_allclose(float(auroc.compute()), roc_auc_score(target, preds), atol=1e-6)

    # CapacityBuffer states across processes: per-rank buffers hold UNEVEN
    # fill counts (120 vs 80); _sync_dist materializes the filled prefixes
    # and gathers through the same uneven pad/trim path
    auroc_buf = AUROC(sample_capacity=256)
    auroc_buf.update(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))
    np.testing.assert_allclose(float(auroc_buf.compute()), roc_auc_score(target, preds), atol=1e-6)

    # dist_sync_on_step: the step value returned by forward must be the
    # GLOBAL batch value (sync happens inside forward, both ranks in the
    # collective simultaneously)
    acc_step = Accuracy(dist_sync_on_step=True)
    step_val = acc_step.forward(jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))
    np.testing.assert_allclose(float(step_val), ref, atol=1e-6)
    print(f"rank {{rank}} OK", flush=True)
    """
)


def test_two_process_dcn_sync(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=repo, coord=f"127.0.0.1:{port}"))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    deadline = time.monotonic() + 150  # one shared budget for both ranks
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            # keep outputs already drained from finished ranks; only the
            # not-yet-communicated procs still have pipes to read
            outs = outs + [q.communicate()[0] or "" for q in procs[len(outs):]]
            if any(f"rank {i} init" in o for i, o in enumerate(outs)):
                # coordinator handshake succeeded: a hang past this point is
                # a real deadlock in the gather path, not an env problem
                pytest.fail(f"workers hung after jax.distributed init:\n{outs}")
            pytest.skip("jax.distributed coordinator timed out in this environment")
        outs.append(out)
    combined = "\n".join(outs)
    if any(p.returncode != 0 for p in procs) and (
        "Multiprocess computations aren't implemented on the CPU backend" in combined
    ):
        # Known pre-existing tier-1 gap on single-host CPU containers: this
        # jax build's CPU backend cannot execute cross-process collectives
        # at all — every process_allgather raises INVALID_ARGUMENT, the
        # ft.retry policy exhausts and degrades every sync to per-host
        # partials, and the workers' global-value assertions then (rightly)
        # fail against local-only state. That is an environment capability
        # limit, not a gather-path bug; the in-process 8-device virtual
        # mesh tests cover the collective math, and this test runs for real
        # wherever the backend supports multiprocess execution.
        pytest.skip(
            "jax CPU backend in this container cannot run multiprocess collectives"
            " (process_allgather raises 'Multiprocess computations aren't implemented"
            " on the CPU backend'); DCN sync degrades to per-host partials by design,"
            " so the global-value assertions cannot hold here."
        )
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {i} failed:\n{out}"
        assert f"rank {i} OK" in out
