"""CI smoke: topology churn is bitwise-invisible at the root.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.elastic_smoke``
(the CI step does, mirroring ``chaos_smoke``). One orchestrated arm plus a
loadgen arm:

* **orchestrated arm** — 64 clients ship 4 cumulative snapshot intervals
  through a (2, 4) :class:`~metrics_tpu.serve.ElasticFleet`, consulting
  the consistent-hash :class:`~metrics_tpu.serve.Router` **per ship**,
  under a seeded 10% :class:`~metrics_tpu.ft.faults.WireChaos` schedule
  (drop / duplicate / reorder / corrupt / delay). Between intervals the
  topology churns through every rebalance kind via the seeded chaos
  injectors: a node **JOINS** (admission protocol: warm, readiness probe,
  ring re-homing), a leaf **DRAINS** (queue folded to empty, final
  cumulative ship, client handoff, tombstoned retirement — no payload it
  accepted may be lost), a leaf **SPLITS** (sibling join), and an
  intermediate is **HARD-KILLED** mid-run and rebuilt by the Supervisor.
  The final root ``/query`` over HTTP must be **bitwise-equal to the flat
  oracle merge of exactly the accepted snapshots**, every rebalance must
  be visible in ``serve.rebalances{kind=}`` / ``chaos.injected{kind=}`` /
  ``serve.rebalance_ms`` / ``serve.heal_ms``, and every client the
  drained node held must be re-homed at a watermark >= the one it had
  there (the no-loss half, asserted directly).
* **loadgen arm** — the churn bench row's harness at 1k clients
  (``churn=True``, join + intermediate kill inside the timed window) with
  ``verify=True``: the root stays bitwise-equal while the rate row is
  measured.

Why the hard-kill targets an intermediate, never a leaf: same argument as
``chaos_smoke`` — interior state reconstructs from the children's next
cumulative ships, so the oracle stays an exact function of the delivery
schedule. Drains may target leaves precisely BECAUSE the drain protocol's
handoff preserves accepted end-client state; that asymmetry (kill loses
nothing interior, drain loses nothing at all) is the contract this smoke
pins.
"""
import json
import os
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

SEED = 20260804
N_CLIENTS = 64
N_INTERVALS = 4
SAMPLES = 64
TENANT = "elastic"
FAN_OUT = (2, 4)


def _factory():
    from metrics_tpu import MaxMetric, SumMetric
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.streaming import StreamingAUROC

    return MetricCollection(
        {"auroc": StreamingAUROC(num_bins=128), "seen": SumMetric(), "peak": MaxMetric()}
    )


def _client_snapshots():
    import numpy as np

    import jax.numpy as jnp

    from metrics_tpu.serve.wire import encode_state

    out = {}
    for c in range(N_CLIENTS):
        cid = f"client-{c:03d}"
        rng = np.random.default_rng(7000 + c)
        coll = _factory()
        blobs = []
        for interval in range(N_INTERVALS):
            preds = jnp.asarray(rng.uniform(0, 1, SAMPLES).astype(np.float32))
            target = jnp.asarray(
                (rng.uniform(0, 1, SAMPLES) < 0.3 + 0.4 * np.asarray(preds)).astype(np.int32)
            )
            coll["auroc"].update(preds, target)
            coll["seen"].update(jnp.asarray(float(SAMPLES)))
            coll["peak"].update(preds)
            blobs.append(encode_state(coll, tenant=TENANT, client_id=cid, watermark=(0, interval)))
        out[cid] = blobs
    return out


def _orchestrated_arm() -> None:
    import numpy as np

    from metrics_tpu import obs
    from metrics_tpu.ft import faults
    from metrics_tpu.serve import (
        AggregationTree,
        Aggregator,
        ElasticFleet,
        MetricsServer,
        ResilienceConfig,
        Supervisor,
    )
    from metrics_tpu.serve.wire import WireFormatError, peek_header

    obs.reset()
    obs.enable()
    snapshots = _client_snapshots()
    # 10% total wire-fault budget, split over all five fates
    chaos = faults.WireChaos(
        SEED, p_drop=0.02, p_duplicate=0.02, p_reorder=0.02, p_corrupt=0.02, p_delay=0.02
    )
    tree = AggregationTree(
        fan_out=FAN_OUT,
        tenants={TENANT: _factory},
        resilience=ResilienceConfig(error_threshold=3),
    )
    fleet = ElasticFleet(tree, seed=SEED)
    supervisor = Supervisor(tree, heartbeat_timeout_s=5.0, name="supervisor", warn=False)

    delivered = set()  # (client_id, interval) delivered uncorrupted + admitted

    def deliver(blobs) -> None:
        for blob in blobs:
            try:
                _, header = peek_header(blob)
            except WireFormatError:
                # corruption mangled the framing itself: route it anywhere
                # live, it is refused either way
                try:
                    fleet.router.route("garbage").ingest(blob)
                except WireFormatError:
                    pass
                continue
            cid = str(header["client"])
            try:
                # the elasticity contract: consult the Router PER SHIP
                fleet.router.route(cid).ingest(blob)
            except WireFormatError:
                pass  # corrupt-in-flight: refused by the crc32
            else:
                delivered.add((cid, int(header["watermark"][1])))

    def deliver_interval(interval: int) -> None:
        for cid in sorted(snapshots):
            _, now_blobs = chaos.plan(snapshots[cid][interval])
            deliver(now_blobs)
        deliver(chaos.end_round())

    # ---- interval 0, then a node JOINS (admission protocol) -------------
    deliver_interval(0)
    fleet.pump()
    joined = faults.join_node(fleet)
    assert joined.name in fleet.router.members()

    # ---- interval 1, then a seeded leaf DRAINS --------------------------
    deliver_interval(1)
    fleet.pump()
    victim_name = chaos.choice(sorted(fleet.router.members()))
    victim = tree.node_by_name(victim_name)
    # capture what the draining node holds: every one of these must exist
    # somewhere in the fleet at >= this watermark after the drain (the
    # "no payload accepted by a draining node is lost" acceptance check)
    held = {
        cid: victim.aggregator.client_watermark(TENANT, cid)
        for cid in sorted(victim.aggregator._tenant(TENANT).clients)
        if not cid.startswith("node:")
    }
    summary = faults.drain_node(fleet, victim)
    assert summary["rehomed_clients"] == len(held), summary
    for cid, wm in held.items():
        new_home = fleet.router.route(cid)
        rehomed_wm = new_home.client_watermark(TENANT, cid)
        assert rehomed_wm is not None and rehomed_wm >= wm, (
            f"client {cid} (watermark {wm} on drained {victim_name}) not re-homed:"
            f" {new_home.name} holds {rehomed_wm}"
        )
    fleet.pump()

    # ---- interval 2, then a SPLIT and an intermediate HARD-KILL ---------
    deliver_interval(2)
    fleet.pump()
    split_victim = chaos.choice(sorted(fleet.router.members()))
    sibling = faults.split_node(fleet, split_victim)
    assert sibling.name in fleet.router.members()
    kill_victim = chaos.choice(tree.levels[1])
    faults.kill_node(kill_victim)
    report = supervisor.check()
    assert "dead_node" in {f["kind"] for f in report["findings"]}, report
    actions = supervisor.heal()
    assert any(a["action"] == "rebuild_node" and a["node"] == kill_victim.name for a in actions)
    fleet.pump()

    # ---- interval 3, drain everything chaos still holds, converge -------
    deliver_interval(3)
    deliver(chaos.flush())
    fleet.pump(rounds=3)

    # ---- oracle: flat merge of exactly the accepted snapshots -----------
    accepted = {}
    for cid, interval in delivered:
        if cid not in accepted or interval > accepted[cid]:
            accepted[cid] = interval
    flat = Aggregator("flat-oracle")
    flat.register_tenant(TENANT, _factory)
    for cid, interval in sorted(accepted.items()):
        flat.ingest(snapshots[cid][interval])
    flat.flush()
    flat_tenant = flat._tenant(TENANT)
    if flat_tenant.merged_leaves is None:
        flat_tenant.fold()
    tree.root.aggregator.flush()
    root_tenant = tree.root.aggregator._tenant(TENANT)
    if root_tenant.merged_leaves is None:
        root_tenant.fold()
    assert root_tenant.spec == flat_tenant.spec
    for (path, _), ours, oracle in zip(
        root_tenant.spec, root_tenant.merged_leaves, flat_tenant.merged_leaves
    ):
        assert np.array_equal(np.asarray(ours), np.asarray(oracle)), (
            f"root leaf {'/'.join(path)} differs from the accepted-snapshot oracle"
            " after join+drain+split+kill churn"
        )

    # ---- every churn event and fault is visible in obs ------------------
    for kind in ("join", "drain", "split", "kill"):
        assert obs.get_counter("chaos.injected", kind=kind) >= 1, kind
    for kind, count in chaos.counts.items():
        if kind == "deliver" or count == 0:
            continue
        assert obs.get_counter("chaos.injected", kind=kind) == count, kind
    # split runs AS a join composition but is counted as its own kind
    assert obs.get_counter("serve.rebalances", kind="join") == 1
    assert obs.get_counter("serve.rebalances", kind="drain") == 1
    assert obs.get_counter("serve.rebalances", kind="split") == 1
    rebalance_hist = obs.get_histogram("serve.rebalance_ms", kind="drain")
    assert rebalance_hist is not None and rebalance_hist.count == 1
    heal_hist = obs.get_histogram("serve.heal_ms", kind="rebuild_node")
    assert heal_hist is not None and heal_hist.count >= 1
    assert obs.get_counter("serve.drains", node=victim_name) == 1
    assert obs.get_counter("health.alerts", monitor="supervisor", kind="dead_node") >= 1

    # ---- the HTTP surface agrees and reports itself ready ---------------
    server = MetricsServer(tree.root.aggregator, port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        q = json.load(urllib.request.urlopen(f"{base}/query?tenant={TENANT}", timeout=10))
        offline = tree.root.aggregator.query(TENANT)
        assert q == json.loads(json.dumps(offline)), "HTTP /query != in-process query"
        ready = json.load(urllib.request.urlopen(f"{base}/healthz/ready", timeout=10))
        assert ready["ready"] is True, ready
        metrics = urllib.request.urlopen(f"{base}/metrics", timeout=10).read().decode()
        assert "serve_rebalances" in metrics, "churn counters missing from /metrics"
    finally:
        server.stop()

    faults_injected = sum(v for k, v in chaos.counts.items() if k != "deliver")
    print(
        f"elastic smoke [orchestrated]: {N_CLIENTS} clients x {N_INTERVALS} intervals at"
        f" 10% wire faults ({faults_injected} injected) through join({joined.name}) +"
        f" drain({victim_name}, {len(held)} clients re-homed, none lost) +"
        f" split({split_victim}->{sibling.name}) + hard-kill({kill_victim.name}) +"
        " supervised rebuild — root /query bitwise-equal to the accepted-snapshot"
        " oracle, every rebalance visible in obs counters",
        flush=True,
    )


def _loadgen_arm() -> None:
    from metrics_tpu import obs
    from metrics_tpu.serve.loadgen import run_loadgen

    obs.reset()
    out = run_loadgen(
        n_clients=1000,
        fan_out=(4, 16),
        payloads_per_client=3,
        samples_per_payload=128,
        num_bins=128,
        seed=SEED,
        verify=True,
        churn=True,
    )
    assert out["verified_bitwise"] is True
    assert out["churn_events"].get("joined") and out["churn_events"].get("killed")
    assert out["serve_churn_merges_per_s"] > 0
    print(
        f"elastic smoke [loadgen]: 1000 clients x 3 snapshots,"
        f" {out['churn_events']['joined']} joined + {out['churn_events']['killed']}"
        f" hard-killed+healed mid-window at"
        f" {out['serve_churn_merges_per_s']:.0f} merges/s — root bitwise-equal",
        flush=True,
    )


def main() -> None:
    _orchestrated_arm()
    _loadgen_arm()
    print("elastic smoke OK", flush=True)


if __name__ == "__main__":
    main()
