"""MetricLogger lifecycle (reference ``integrations/test_lightning.py``).

The reference asserts Lightning's log/accumulate/reset semantics per epoch:
on_step values are batch-local, on_epoch values aggregate the whole epoch,
and epoch boundaries reset accumulation. Same contract here, without the
trainer.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Accuracy, MeanSquaredError
from metrics_tpu.integrations import MetricLogger


def test_logger_epoch_lifecycle():
    rng = np.random.default_rng(0)
    logger = MetricLogger()
    acc = Accuracy()

    for epoch in range(2):
        epoch_preds, epoch_target = [], []
        for _ in range(3):
            p = rng.uniform(0, 1, 32)
            t = rng.integers(0, 2, 32)
            epoch_preds.append(p)
            epoch_target.append(t)
            logger.log("train/acc", acc, jnp.asarray(p), jnp.asarray(t))
            logger.log("train/loss", float(p.mean()))
            step = logger.step_values()
            # on_step value is batch-local
            np.testing.assert_allclose(
                float(step["train/acc"]), ((p >= 0.5).astype(int) == t).mean(), atol=1e-6
            )
        vals = logger.epoch_values()
        P, T = np.concatenate(epoch_preds), np.concatenate(epoch_target)
        # on_epoch value aggregates exactly this epoch (reset isolates epochs)
        np.testing.assert_allclose(float(vals["train/acc"]), ((P >= 0.5).astype(int) == T).mean(), atol=1e-6)
        np.testing.assert_allclose(vals["train/loss"], np.mean([p.mean() for p in epoch_preds]), atol=1e-6)

    assert len(logger.history) == 2
    # reset cleared state: next epoch starts fresh
    assert acc._update_count == 0


def test_logger_multiple_metrics_and_no_update():
    logger = MetricLogger()
    mse = MeanSquaredError()
    acc = Accuracy()
    logger.log("mse", mse, jnp.asarray([1.0, 2.0]), jnp.asarray([1.0, 2.0]))
    # acc never updated: epoch_values must skip it, not warn/compute garbage
    logger._metrics["acc"] = acc
    vals = logger.epoch_values()
    assert "acc" not in vals
    assert float(vals["mse"]) == 0.0


def test_logger_scalar_args_rejected():
    logger = MetricLogger()
    with pytest.raises(ValueError, match="only valid when logging a Metric"):
        logger.log("x", 1.0, jnp.asarray([1.0]))


def test_logger_rebind_rejected():
    logger = MetricLogger()
    logger.log("acc", Accuracy(), jnp.asarray([0.9]), jnp.asarray([1]))
    with pytest.raises(ValueError, match="different Metric object"):
        logger.log("acc", Accuracy(), jnp.asarray([0.9]), jnp.asarray([1]))


def test_logger_rebind_after_epoch_reset_allowed():
    """A metric constructed per epoch is fine: the old one was reset."""
    logger = MetricLogger()
    for _ in range(2):
        acc = Accuracy()
        logger.log("acc", acc, jnp.asarray([0.9, 0.2]), jnp.asarray([1, 0]))
        vals = logger.epoch_values()
        assert float(vals["acc"]) == 1.0


def test_logger_failed_first_log_leaves_no_registration():
    logger = MetricLogger()
    with pytest.raises(Exception):
        logger.log("acc", Accuracy(), jnp.asarray([[0.9]]), jnp.asarray([1, 0, 1]))
    assert "acc" not in logger._metrics
    logger.log("acc", 0.5)  # name is free for a scalar now


def test_logger_name_collision_rejected():
    logger = MetricLogger()
    logger.log("acc", Accuracy(), jnp.asarray([0.9]), jnp.asarray([1]))
    with pytest.raises(ValueError, match="already logged as a Metric"):
        logger.log("acc", 0.97)
    logger.log("loss", 0.5)
    with pytest.raises(ValueError, match="already logged as a scalar"):
        logger.log("loss", Accuracy(), jnp.asarray([0.9]), jnp.asarray([1]))


def test_logger_on_step_false_accumulates_without_step_value():
    rng = np.random.default_rng(2)
    logger = MetricLogger()
    acc = Accuracy()
    allp, allt = [], []
    for _ in range(3):
        p, t = rng.uniform(0, 1, 16), rng.integers(0, 2, 16)
        allp.append(p), allt.append(t)
        out = logger.log("val/acc", acc, jnp.asarray(p), jnp.asarray(t), on_step=False)
        assert out is None
        assert "val/acc" not in logger.step_values()
    P, T = np.concatenate(allp), np.concatenate(allt)
    vals = logger.epoch_values()
    np.testing.assert_allclose(float(vals["val/acc"]), ((P >= 0.5).astype(int) == T).mean(), atol=1e-6)


def test_logger_step_values_survive_epoch_close():
    logger = MetricLogger()
    acc = Accuracy()
    logger.log("acc", acc, jnp.asarray([0.9, 0.2]), jnp.asarray([1, 0]))
    logger.epoch_values()  # close epoch first ...
    step = logger.step_values()  # ... final batch's step values still there
    assert "acc" in step


def test_logger_history_archives_across_epochs():
    """history[e] is exactly what epoch_values() returned for epoch e."""
    rng = np.random.default_rng(7)
    logger = MetricLogger()
    acc = Accuracy()
    returned = []
    for epoch in range(3):
        for _ in range(2):
            p, t = rng.uniform(0, 1, 16), rng.integers(0, 2, 16)
            logger.log("acc", acc, jnp.asarray(p), jnp.asarray(t))
            logger.log("loss", float(p.mean()))
        returned.append(logger.epoch_values())
    assert len(logger.history) == 3
    for archived, ret in zip(logger.history, returned):
        assert archived.keys() == ret.keys() == {"acc", "loss"}
        assert float(archived["acc"]) == float(ret["acc"])
        assert archived["loss"] == ret["loss"]
    assert acc._update_count == 0  # epoch close reset the metric


def test_logger_epoch_values_without_reset_does_not_archive():
    logger = MetricLogger()
    acc = Accuracy()
    logger.log("acc", acc, jnp.asarray([0.9, 0.2]), jnp.asarray([1, 0]))
    peek = logger.epoch_values(reset=False)
    assert float(peek["acc"]) == 1.0
    assert logger.history == []
    assert acc._update_count == 1  # state survives the peek
    final = logger.epoch_values()
    assert float(final["acc"]) == 1.0
    assert len(logger.history) == 1


def test_logger_mixed_metric_and_scalar():
    """Metric objects and plain scalars share one epoch cleanly."""
    logger = MetricLogger()
    acc = Accuracy()
    logger.log("acc", acc, jnp.asarray([0.9, 0.2]), jnp.asarray([1, 0]))
    logger.log("loss", 0.5)
    logger.log("lr", 1e-3)
    step = logger.step_values()
    assert set(step) == {"acc", "loss", "lr"}
    logger.log("acc", acc, jnp.asarray([0.1]), jnp.asarray([1]))
    logger.log("loss", 0.3)
    vals = logger.epoch_values()
    assert set(vals) == {"acc", "loss", "lr"}
    np.testing.assert_allclose(float(vals["acc"]), 2 / 3, atol=1e-6)
    np.testing.assert_allclose(vals["loss"], 0.4, atol=1e-9)  # mean of the buffer
    assert vals["lr"] == pytest.approx(1e-3)
    # scalar buffers cleared with the epoch
    assert logger.epoch_values() == {}


def test_logger_obs_history_archived_when_enabled():
    import metrics_tpu.obs as obs

    logger = MetricLogger()
    acc = Accuracy()
    logger.log("acc", acc, jnp.asarray([0.9, 0.2]), jnp.asarray([1, 0]))
    logger.epoch_values()
    assert logger.obs_history == [None]  # disabled epoch: placeholder keeps alignment
    prev = obs.enable()
    try:
        obs.reset()
        logger.log("acc", acc, jnp.asarray([0.9]), jnp.asarray([1]))
        logger.epoch_values()
        # index-parallel with history even across the mid-run toggle
        assert len(logger.obs_history) == len(logger.history) == 2
        snap = logger.obs_history[1]
        assert snap["counters"]["metric.forwards{metric=Accuracy}"] >= 1
        assert "obs" not in logger.history[-1]
    finally:
        obs.enable(prev)
        obs.reset()
