"""Plumbing tests for the bench regression gate (``benchmarks/compare.py``).

These assert the gate's CONTRACT, not performance: identical inputs pass
(exit 0), an injected 2x slowdown fails (nonzero exit), cross-device
comparisons are refused with their own exit code and a clear message, both
record shapes in the tree load, the noise-awareness rules (n_fast /
probe normalization) hold, and the CLI surfaces (`python -m
benchmarks.compare`, `bench.py --compare`) expose all of it.
"""
import copy
import json
import os
import subprocess
import sys

import pytest

from benchmarks.compare import (
    EXIT_OK,
    EXIT_REFUSED,
    EXIT_REGRESSED,
    BenchRecord,
    CompareRefused,
    PROBE_CLASS,
    compare_records,
    load_record,
    render_report,
    trend_table,
)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_compare_fixture.json")


def _fixture_dict() -> dict:
    with open(FIXTURE) as f:
        return json.load(f)


def _write(tmp_path, name, data) -> str:
    path = str(tmp_path / name)
    with open(path, "w") as f:
        json.dump(data, f)
    return path


def _slowed(data: dict, factor: float, metrics=None) -> dict:
    out = copy.deepcopy(data)
    for row in out["rows"]:
        if metrics is None or row["metric"] in metrics:
            row["value"] = row["value"] * factor
            if row.get("fast_mode_median") is not None:
                row["fast_mode_median"] = row["fast_mode_median"] * factor
    return out


class TestLoadRecord:
    def test_json_record_shape(self):
        rec = load_record(FIXTURE)
        assert rec.device_kind == "cpu" and rec.source == "record"
        assert rec.process_count == 1 and rec.device_count == 8
        assert "collection_prf1_500_update_groups_on" in rec.rows
        assert "device_kind=cpu" in rec.header() and "hosts=1" in rec.header()

    def test_driver_tail_shape(self):
        rec = load_record(os.path.join(REPO, "BENCH_r05.json"))
        assert rec.source == "driver_tail" and rec.device_kind is None
        assert "accuracy_1M_update_compute_wallclock" in rec.rows

    def test_unreadable_and_malformed_refused(self, tmp_path):
        with pytest.raises(CompareRefused, match="cannot read"):
            load_record(str(tmp_path / "missing.json"))
        bad = _write(tmp_path, "bad.json", {"neither": "shape"})
        with pytest.raises(CompareRefused, match="unrecognized"):
            load_record(bad)


class TestGate:
    def test_identical_inputs_pass(self):
        rec = load_record(FIXTURE)
        result = compare_records(rec, rec)
        assert result["exit_code"] == EXIT_OK and result["regressions"] == []
        verdicts = {r["metric"]: r["verdict"] for r in result["rows"]}
        assert verdicts["probe_elementwise_1Mx10"] == "probe"  # probes never gate
        assert verdicts["flaky_row_one_fast_sample"] == "low-confidence"

    def test_injected_2x_slowdown_fails(self, tmp_path):
        old = load_record(FIXTURE)
        new = load_record(_write(tmp_path, "slow.json", _slowed(_fixture_dict(), 2.0)))
        result = compare_records(old, new)
        assert result["exit_code"] == EXIT_REGRESSED
        assert "collection_prf1_500_update_groups_on" in result["regressions"]
        # the noise rules hold even amid a regression: probes and
        # low-confidence rows are reported but never in the gate list
        assert "probe_elementwise_1Mx10" not in result["regressions"]
        assert "flaky_row_one_fast_sample" not in result["regressions"]
        report = render_report(result)
        assert "GATE: FAIL" in report and "REGRESSION" in report

    def test_probe_normalization_cancels_chip_state(self, tmp_path):
        """A row 2x slower while its class probe is also 2x slower is chip
        state, not code — the normalized ratio gates, and reads 1.0."""
        probed = "accuracy_1M_update_compute_wallclock"
        probe = PROBE_CLASS[probed]
        slowed = _slowed(_fixture_dict(), 2.0, metrics={probed, probe})
        old = load_record(FIXTURE)
        new = load_record(_write(tmp_path, "chipslow.json", slowed))
        result = compare_records(old, new)
        row = next(r for r in result["rows"] if r["metric"] == probed)
        assert row["norm_ratio"] == pytest.approx(1.0)
        assert row["verdict"] == "ok" and probed not in result["regressions"]

    def test_probe_normalized_regression_still_fires(self, tmp_path):
        """Row 3x slower, probe unchanged: the normalized ratio shows the
        real regression and the gate fails."""
        probed = "accuracy_1M_update_compute_wallclock"
        slowed = _slowed(_fixture_dict(), 3.0, metrics={probed})
        result = compare_records(
            load_record(FIXTURE), load_record(_write(tmp_path, "rowslow.json", slowed))
        )
        row = next(r for r in result["rows"] if r["metric"] == probed)
        assert row["norm_ratio"] == pytest.approx(3.0)
        assert probed in result["regressions"]

    def test_rate_row_gate_is_inverted(self, tmp_path):
        """Throughput rows (unit="/s" / *_per_s) regress when they go DOWN:
        halved merges/sec must fail the gate, doubled must read improved."""
        data = _fixture_dict()
        data["rows"].append(
            {"metric": "serve_ingest_merges_per_s", "value": 10000.0, "unit": "/s", "vs_baseline": 1.0}
        )
        old = load_record(_write(tmp_path, "rate_old.json", data))

        halved = copy.deepcopy(data)
        next(r for r in halved["rows"] if r["metric"] == "serve_ingest_merges_per_s")["value"] = 5000.0
        result = compare_records(old, load_record(_write(tmp_path, "rate_half.json", halved)))
        assert "serve_ingest_merges_per_s" in result["regressions"]
        row = next(r for r in result["rows"] if r["metric"] == "serve_ingest_merges_per_s")
        assert row["ratio"] == pytest.approx(2.0)  # gate ratio: old/new for rates
        assert "higher is better" in row["note"]

        doubled = copy.deepcopy(data)
        next(r for r in doubled["rows"] if r["metric"] == "serve_ingest_merges_per_s")["value"] = 20000.0
        result = compare_records(old, load_record(_write(tmp_path, "rate_double.json", doubled)))
        row = next(r for r in result["rows"] if r["metric"] == "serve_ingest_merges_per_s")
        assert row["verdict"] == "improved"
        assert result["exit_code"] == EXIT_OK

    def test_rate_row_probe_normalization_cancels_chip_state(self, tmp_path):
        """Throughput halved while the class probe's LATENCY doubled is the
        same chip state, not code: throughput x probe latency is the
        invariant, and the normalized ratio must read 1.0."""
        probe = PROBE_CLASS["serve_ingest_merges_per_s"]
        data = _fixture_dict()
        data["rows"].append(
            {"metric": "serve_ingest_merges_per_s", "value": 10000.0, "unit": "/s", "vs_baseline": 1.0}
        )
        old = load_record(_write(tmp_path, "rn_old.json", data))
        chipslow = _slowed(copy.deepcopy(data), 2.0, metrics={probe})
        next(r for r in chipslow["rows"] if r["metric"] == "serve_ingest_merges_per_s")["value"] = 5000.0
        result = compare_records(old, load_record(_write(tmp_path, "rn_new.json", chipslow)))
        row = next(r for r in result["rows"] if r["metric"] == "serve_ingest_merges_per_s")
        assert row["norm_ratio"] == pytest.approx(1.0)
        assert row["verdict"] == "ok"

    def test_rate_row_duplicates_keep_the_highest(self):
        """rows_by_metric keeps the BEST value per duplicate metric — for a
        rate row that is the highest, not the lowest."""
        from benchmarks.compare import rows_by_metric

        rows = [
            {"metric": "x_per_s", "value": 100.0, "unit": "/s"},
            {"metric": "x_per_s", "value": 300.0, "unit": "/s"},
            {"metric": "y_ms", "value": 3.0, "unit": "ms"},
            {"metric": "y_ms", "value": 1.0, "unit": "ms"},
        ]
        out = rows_by_metric(rows)
        assert out["x_per_s"]["value"] == 300.0
        assert out["y_ms"]["value"] == 1.0

    def test_threshold_is_configurable(self, tmp_path):
        old = load_record(FIXTURE)
        new = load_record(_write(tmp_path, "slow13.json", _slowed(_fixture_dict(), 1.3)))
        assert compare_records(old, new, threshold=1.5)["exit_code"] == EXIT_OK
        assert compare_records(old, new, threshold=1.2)["exit_code"] == EXIT_REGRESSED

    def test_new_and_removed_rows_reported_not_gated(self, tmp_path):
        data = _fixture_dict()
        data["rows"] = [r for r in data["rows"] if r["metric"] != "accuracy_1M_update_compute_wallclock"]
        data["rows"].append({"metric": "brand_new_row", "value": 1.0, "unit": "ms", "vs_baseline": 1.0})
        result = compare_records(load_record(FIXTURE), load_record(_write(tmp_path, "churn.json", data)))
        verdicts = {r["metric"]: r["verdict"] for r in result["rows"]}
        assert verdicts["brand_new_row"] == "new"
        assert verdicts["accuracy_1M_update_compute_wallclock"] == "removed"
        assert result["exit_code"] == EXIT_OK


class TestCrossDevice:
    def test_refused_with_clear_message(self, tmp_path):
        other = _fixture_dict()
        other["device_kind"] = "TPU v4"
        old = load_record(FIXTURE)
        new = load_record(_write(tmp_path, "tpu.json", other))
        with pytest.raises(CompareRefused, match="TPU v4") as err:
            compare_records(old, new)
        assert "cpu" in str(err.value)

    def test_override_flag_allows_it(self, tmp_path):
        other = _fixture_dict()
        other["device_kind"] = "TPU v4"
        new = load_record(_write(tmp_path, "tpu.json", other))
        result = compare_records(load_record(FIXTURE), new, allow_cross_device=True)
        assert result["exit_code"] == EXIT_OK

    def test_headerless_driver_tail_compares_with_warning(self):
        rec = load_record(os.path.join(REPO, "BENCH_r05.json"))
        result = compare_records(rec, rec)
        assert result["exit_code"] == EXIT_OK
        assert "WARNING" in render_report(result)


class TestPriorRounds:
    def test_rate_row_identified_by_unit_alone_keeps_highest(self, tmp_path, monkeypatch):
        """bench.py's best-prior scans drop the row's ``unit`` field, so a
        rate row whose name does NOT end in ``_per_s`` must still invert to
        max() via the rate-name set _prior_rounds now returns (regression:
        the gate silently compared against the WORST prior round)."""
        import glob

        import bench

        paths = []
        for i, value in enumerate((3.0, 5.0)):
            row = {"metric": "serve_throughput", "value": value, "unit": "/s"}
            path = tmp_path / f"BENCH_r9{i}.json"
            path.write_text(json.dumps({"tail": json.dumps(row)}))
            paths.append(str(path))
        monkeypatch.setattr(glob, "glob", lambda pattern: paths)
        rounds, rate_names = bench._prior_rounds()
        assert "serve_throughput" in rate_names
        assert [r["serve_throughput"] for r in rounds] == [3.0, 5.0]
        # best prior = HIGHEST throughput, despite the non-_per_s name
        assert bench._best_prior_values()["serve_throughput"] == 5.0


class TestTrend:
    def test_trend_table_across_rounds(self):
        paths = sorted(
            os.path.join(REPO, f) for f in os.listdir(REPO)
            if f.startswith("BENCH_r0") and f.endswith(".json")
        )
        table = trend_table(paths)
        assert "accuracy_1M_update_compute_wallclock" in table
        assert table.count("|") > len(paths) * 3  # metric x round grid rendered


class TestCli:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, "-m", "benchmarks.compare", *args],
            capture_output=True, text=True, timeout=120, cwd=REPO,
        )

    def test_pass_fail_refuse_exit_codes(self, tmp_path):
        assert self._run(FIXTURE, FIXTURE).returncode == EXIT_OK
        slow = _write(tmp_path, "slow.json", _slowed(_fixture_dict(), 2.0))
        out = self._run(FIXTURE, slow)
        assert out.returncode == EXIT_REGRESSED
        assert "GATE: FAIL" in out.stdout
        other = _fixture_dict()
        other["device_kind"] = "TPU v4"
        tpu = _write(tmp_path, "tpu.json", other)
        out = self._run(FIXTURE, tpu)
        assert out.returncode == EXIT_REFUSED
        assert "refusing to compare across device kinds" in out.stderr

    def test_report_header_records_device_and_jax(self):
        out = self._run(FIXTURE, FIXTURE)
        assert "device_kind=cpu" in out.stdout
        assert "jax=0.4.37" in out.stdout
        assert "hosts=1" in out.stdout

    def test_markdown_written(self, tmp_path):
        md = str(tmp_path / "report.md")
        assert self._run(FIXTURE, FIXTURE, "--markdown", md).returncode == EXIT_OK
        with open(md) as f:
            assert "# Bench comparison" in f.read()


def test_bench_cli_exposes_compare_flags():
    """bench.py's CLI accepts --compare/--compare-threshold (CI calls it)."""
    out = subprocess.run(
        [sys.executable, "bench.py", "--help"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    assert "--compare" in out.stdout and "--compare-threshold" in out.stdout


class TestTrendGaps:
    def test_rounds_missing_a_row_render_as_gaps(self, tmp_path):
        """Rows added in later rounds (e.g. the round-7 collection_* rows)
        render as — in earlier columns instead of breaking the table."""
        old = _fixture_dict()
        new = copy.deepcopy(old)
        new["rows"] = new["rows"] + [
            {"metric": "collection12_1M_epoch_wallclock", "value": 1.5, "unit": "ms"},
            {"metric": "collection12_launch_count", "value": 1.0, "unit": "launches"},
        ]
        p_old = _write(tmp_path, "r01.json", old)
        p_new = _write(tmp_path, "r02.json", new)
        table = trend_table([p_old, p_new])
        assert "collection12_1M_epoch_wallclock | — | 1.500" in table
        assert "collection12_launch_count | — | 1.000" in table

    def test_bench_cli_trend_mode(self, tmp_path):
        """bench.py --trend renders the table without running the sweep."""
        old = _fixture_dict()
        new = copy.deepcopy(old)
        new["rows"] = new["rows"] + [
            {"metric": "collection12_1M_epoch_wallclock", "value": 1.5, "unit": "ms"}
        ]
        p_old = _write(tmp_path, "r01.json", old)
        p_new = _write(tmp_path, "r02.json", new)
        out = subprocess.run(
            [sys.executable, "bench.py", "--trend", p_old, p_new],
            capture_output=True, text=True, timeout=120, cwd=REPO,
        )
        assert out.returncode == 0, out.stderr
        assert "# Bench trend" in out.stdout
        assert "collection12_1M_epoch_wallclock | — | 1.500" in out.stdout
