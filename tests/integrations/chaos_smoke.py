"""CI smoke: the serving fleet self-heals under a seeded chaos schedule.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.chaos_smoke`` (the
CI test job does, mirroring ``serve_smoke``). Two arms:

* **loadgen arm** — the 1k-client / 3-level loadgen under a 10% seeded
  fault schedule (drops, duplicates, reordering, payload corruption
  refused by the wire crc32), ``verify=True``: the root state must be
  BITWISE equal to a flat oracle merge of exactly the accepted snapshots.
* **orchestrated arm** — a smaller tree under the full fault set at once:
  the seeded :class:`~metrics_tpu.ft.faults.WireChaos` delivery schedule,
  PLUS one hard-killed node (seeded pick among root/intermediates — the
  in-process SIGKILL analogue; the real-signal arm is ``serve_smoke``)
  detected and rebuilt by the :class:`~metrics_tpu.serve.Supervisor`
  (root restored from its checkpoint, ship sequences resumed above the
  parent's watermarks), PLUS a leaf subtree partitioned mid-stream and
  healed, PLUS a NaN-poisoning client (quarantined) and a
  corrupt-byte-spewing client (circuit opened). The final root ``/query``
  over HTTP must be bitwise-equal to the flat oracle merge of the
  accepted snapshots, and EVERY injected fault must be visible in obs
  counters (``chaos.injected{kind=}``, ``serve.quarantined``,
  ``serve.circuit_open``, ``health.alerts{monitor=supervisor,kind=}``).

Why the kill targets an interior node or the root, never a leaf: interior
state is reconstructable from the children's next cumulative ships (and
the root additionally from its checkpoint), so the oracle stays an exact
function of the delivery schedule. A killed LEAF loses end-client
snapshots until the at-least-once redelivery — recoverable in production,
but the oracle would then depend on the redelivery schedule too.
"""
import json
import os
import random
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

SEED = 20260803
N_CLIENTS = 64
N_INTERVALS = 3
SAMPLES = 64
TENANT = "chaos"
FAN_OUT = (2, 4)
HEARTBEAT_S = 0.3


def _factory():
    from metrics_tpu import MaxMetric, SumMetric
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.streaming import StreamingAUROC

    return MetricCollection(
        {"auroc": StreamingAUROC(num_bins=128), "seen": SumMetric(), "peak": MaxMetric()}
    )


def _client_snapshots():
    import numpy as np

    import jax.numpy as jnp

    from metrics_tpu.serve.wire import encode_state

    out = {}
    for c in range(N_CLIENTS):
        cid = f"client-{c:03d}"
        rng = np.random.default_rng(4000 + c)
        coll = _factory()
        blobs = []
        for interval in range(N_INTERVALS):
            preds = jnp.asarray(rng.uniform(0, 1, SAMPLES).astype(np.float32))
            target = jnp.asarray(
                (rng.uniform(0, 1, SAMPLES) < 0.3 + 0.4 * np.asarray(preds)).astype(np.int32)
            )
            coll["auroc"].update(preds, target)
            coll["seen"].update(jnp.asarray(float(SAMPLES)))
            coll["peak"].update(preds)
            blobs.append(encode_state(coll, tenant=TENANT, client_id=cid, watermark=(0, interval)))
        out[cid] = blobs
    return out


def _poisoned_blob():
    import jax.numpy as jnp

    from metrics_tpu.serve.wire import encode_state

    coll = _factory()
    coll["seen"].update(jnp.asarray(1.0))
    coll["seen"].value = jnp.asarray(float("nan"))  # a buggy client's 0/0
    return encode_state(coll, tenant=TENANT, client_id="poison-client", watermark=(0, 0))


def _loadgen_arm():
    from metrics_tpu.serve.loadgen import run_loadgen

    out = run_loadgen(
        n_clients=1000,
        fan_out=(4, 16),
        payloads_per_client=2,
        samples_per_payload=128,
        num_bins=128,
        seed=SEED,
        verify=True,
        fault_rate=0.10,
    )
    assert out["verified_bitwise"] is True
    counts = out["chaos_counts"]
    for kind in ("drop", "duplicate", "reorder", "corrupt"):
        assert counts[kind] > 0, f"10% schedule over 2000 payloads never drew {kind}"
    assert out["refused_corrupt"] == counts["corrupt"]
    print(
        f"chaos smoke [loadgen]: 1000 clients x 2 snapshots at 10% faults"
        f" ({ {k: v for k, v in counts.items() if k != 'deliver'} }) — root bitwise-equal"
        " to the accepted-snapshot oracle",
        flush=True,
    )


def _orchestrated_arm(tmp: str) -> None:
    import time

    import numpy as np

    from metrics_tpu import obs
    from metrics_tpu.ft import faults
    from metrics_tpu.serve import (
        AggregationTree,
        Aggregator,
        CircuitOpenError,
        MetricsServer,
        ResilienceConfig,
        Supervisor,
    )
    from metrics_tpu.serve.wire import WireFormatError, peek_header

    obs.reset()  # the loadgen arm's counters share the process-global registry
    obs.enable()
    snapshots = _client_snapshots()
    chaos = faults.WireChaos(
        SEED, p_drop=0.03, p_duplicate=0.03, p_reorder=0.03, p_corrupt=0.03, p_delay=0.03
    )
    tree = AggregationTree(
        fan_out=FAN_OUT,
        tenants={TENANT: _factory},
        checkpoint_root=os.path.join(tmp, "root-ckpt"),
        resilience=ResilienceConfig(error_threshold=3),
    )
    supervisor = Supervisor(tree, heartbeat_timeout_s=HEARTBEAT_S, name="supervisor", warn=False)

    delivered = set()  # (client_id, interval) delivered uncorrupted

    def client_index(blob: bytes) -> int:
        _, header = peek_header(blob)
        return int(str(header["client"]).rsplit("-", 1)[1])

    def deliver(blobs) -> None:
        for blob in blobs:
            c = client_index(blob)
            try:
                tree.leaf_for(c).ingest(blob)
            except WireFormatError:
                pass  # corrupt-in-flight: refused by the crc32, counted below
            else:
                _, header = peek_header(blob)
                delivered.add((str(header["client"]), int(header["watermark"][1])))

    def deliver_interval(interval: int) -> None:
        for cid in sorted(snapshots):
            _, now_blobs = chaos.plan(snapshots[cid][interval])
            deliver(now_blobs)
        deliver(chaos.end_round())

    # ---- interval 0, then checkpoint and hard-kill a seeded node --------
    deliver_interval(0)
    tree.pump()
    tree.save()
    victim = chaos.choice([tree.root] + tree.levels[1])  # root or an intermediate
    faults.kill_node(victim)
    report = supervisor.check()
    kinds = {f["kind"] for f in report["findings"]}
    assert "dead_node" in kinds, report
    actions = supervisor.heal()
    assert any(a["action"] == "rebuild_node" and a["node"] == victim.name for a in actions)
    assert not victim.is_dead
    # re-populate the healed node's view from its children's cumulative
    # re-ships (and every parent's child slots — the slot-age heartbeat
    # only watches children it has heard from at least once)
    tree.pump()

    # ---- interval 1 under a leaf partition, healed afterwards -----------
    partitioned_leaf = tree.leaves[-1]
    with faults.partition(partitioned_leaf):
        deliver_interval(1)
        tree.pump()
        time.sleep(HEARTBEAT_S + 0.1)
        tree.pump()  # other children refresh; the partitioned ship drops
        report = supervisor.check()
        stale = [f for f in report["findings"] if f["kind"] == "stale_child"]
        assert any(f"node:{partitioned_leaf.name}" in f["detail"] for f in stale), report

    # ---- hostile clients: poison (quarantine) and corruption (breaker) --
    poison_leaf = tree.leaf_for(0)
    poison_leaf.ingest(_poisoned_blob())
    poison_leaf.flush()
    assert poison_leaf.firewall.is_quarantined(TENANT, "poison-client")

    from metrics_tpu.serve.wire import encode_state

    flaky_leaf = tree.leaf_for(1)
    flaky_rng = random.Random(SEED + 1)
    circuit_opened = False
    flaky_coll = _factory()
    for i in range(4):
        # a DISTINCT identity (never in the oracle set) that only ever
        # ships corrupt bytes — its circuit must open, nobody else's
        bad = faults.corrupt_payload(
            encode_state(flaky_coll, tenant=TENANT, client_id="flaky-client", watermark=(0, i)),
            flaky_rng,
        )
        try:
            flaky_leaf.ingest(bad)
        except WireFormatError:
            continue
        except CircuitOpenError:
            circuit_opened = True
            break
    assert circuit_opened and obs.sum_counter("serve.circuit_open") > 0

    # ---- interval 2, drain everything chaos still holds, converge -------
    deliver_interval(2)
    deliver(chaos.flush())
    tree.pump(rounds=3)

    # ---- oracle: flat merge of exactly the accepted snapshots -----------
    accepted = {}
    for cid, interval in delivered:
        if cid not in accepted or interval > accepted[cid]:
            accepted[cid] = interval
    flat = Aggregator("flat-oracle")
    flat.register_tenant(TENANT, _factory)
    for cid, interval in sorted(accepted.items()):
        flat.ingest(snapshots[cid][interval])
    flat.flush()
    flat_tenant = flat._tenant(TENANT)
    if flat_tenant.merged_leaves is None:
        flat_tenant.fold()

    tree.root.aggregator.flush()
    root_tenant = tree.root.aggregator._tenant(TENANT)
    if root_tenant.merged_leaves is None:
        root_tenant.fold()
    assert root_tenant.spec == flat_tenant.spec
    for (path, _), ours, oracle in zip(
        root_tenant.spec, root_tenant.merged_leaves, flat_tenant.merged_leaves
    ):
        assert np.array_equal(np.asarray(ours), np.asarray(oracle)), (
            f"root leaf {'/'.join(path)} differs from the accepted-snapshot oracle"
        )

    # ---- every injected fault is visible in obs counters ----------------
    for kind, count in chaos.counts.items():
        if kind == "deliver" or count == 0:
            continue
        assert obs.get_counter("chaos.injected", kind=kind) == count, kind
    assert obs.get_counter("chaos.injected", kind="kill") == 1
    assert obs.get_counter("chaos.injected", kind="partition") > 0
    assert obs.sum_counter("serve.quarantined") >= 1
    assert obs.sum_counter("serve.circuit_open") >= 1
    assert obs.get_counter("health.alerts", monitor="supervisor", kind="dead_node") >= 1
    assert obs.get_counter("health.alerts", monitor="supervisor", kind="stale_child") >= 1
    assert obs.sum_counter("serve.wire_errors") > 0

    # ---- the HTTP surface agrees and reports itself ready ---------------
    server = MetricsServer(tree.root.aggregator, port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        q = json.load(urllib.request.urlopen(f"{base}/query?tenant={TENANT}", timeout=10))
        offline = tree.root.aggregator.query(TENANT)
        assert q == json.loads(json.dumps(offline)), "HTTP /query != in-process query"
        live = json.load(urllib.request.urlopen(f"{base}/healthz/live", timeout=10))
        assert live["live"] is True
        ready = json.load(urllib.request.urlopen(f"{base}/healthz/ready", timeout=10))
        assert ready["ready"] is True, ready
    finally:
        server.stop()

    injected = sum(v for k, v in chaos.counts.items() if k != "deliver") + 2  # + kill + partition
    print(
        f"chaos smoke [orchestrated]: {N_CLIENTS} clients x {N_INTERVALS} intervals,"
        f" {injected}+ injected faults (incl. {victim.name} hard-kill + supervised"
        f" rebuild, {partitioned_leaf.name} partition + heal, 1 quarantine, 1 open"
        " circuit) — root /query bitwise-equal to the accepted-snapshot oracle,"
        " every fault visible in obs counters",
        flush=True,
    )


def main() -> None:
    import tempfile

    _loadgen_arm()
    with tempfile.TemporaryDirectory(prefix="chaos_smoke.") as tmp:
        _orchestrated_arm(tmp)
    print("chaos smoke OK", flush=True)


if __name__ == "__main__":
    main()
