"""CI smoke: the time-travel tier keeps its bitwise oracle under chaos.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.history_smoke``
(the CI step does, mirroring ``region_smoke``). One history-armed root
(:class:`~metrics_tpu.serve.Aggregator` with ``history=HistoryConfig``)
fed a loadgen-style client stream under a seeded 10%
:class:`~metrics_tpu.ft.faults.WireChaos` schedule, rings cut live after
every interval, driven through the failure arcs the tier exists for:

* **kill + restore mid-retention** — the root is checkpointed and
  hard-killed (state dropped with no drain), a fresh root restores the
  ring ladder from the :class:`~metrics_tpu.ft.CheckpointManager`
  manifest and keeps cutting; restored-era and post-restore snapshots
  answer from the same ladder.
* **injected metric regression** — two intervals of inverted label
  correlation crater the interval-delta AUROC; the root-evaluated
  :class:`~metrics_tpu.serve.AlertRule` fires **exactly once**
  (edge-triggered through the one-shot-warn machinery), stays firing
  without recounting, and clears on recovery.
* **sustained traffic vs the ring bound** — more intervals than the
  compaction ladder retains: rollups compact by monoid merge, the
  coarsest ring evicts, and evictions are counted under
  ``history.intervals_evicted`` while pre-horizon queries refuse with
  :class:`~metrics_tpu.serve.HistoryRetentionError`.
* **cross-region failover fencing** — a two-region mesh with
  history-armed global views: kill + generation-fenced promotion, then a
  delta range spanning the promotion boundary refuses with
  :class:`~metrics_tpu.serve.GenerationFencedRangeError` while
  per-generation sub-ranges and cumulative reads stay exact, and the
  healed peers' next cumulative re-ship repairs the global range view
  **bitwise**.

Acceptance: EVERY retained interval snapshot — through ring rotation,
rollup compaction, kill+restore and failover — is bitwise-equal to the
flat oracle merge of exactly the snapshots accepted by that cut, and
every injected fault is visible in obs counters.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

SEED = 20260806
N_CLIENTS = 16
N_INTERVALS = 12
SAMPLES = 48
TENANT = "hist"
REGRESSED = (8, 9)  # intervals shipped with inverted label correlation
KILL_AFTER = 5  # cut + checkpoint interval 5, then kill + restore
LEVELS = ((1.0, 2), (2.0, 2), (4.0, 1))  # 12 cuts overflow this ladder
REGIONS = ("east", "west")


def _factory():
    from metrics_tpu import SumMetric
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.streaming import StreamingAUROC

    # both members are delta-queryable (sum subtracts, the sketch's
    # histogram bins subtract) — the alert rule reads interval AUROC
    return MetricCollection({"auroc": StreamingAUROC(num_bins=64), "seen": SumMetric()})


def _client_snapshots():
    import numpy as np

    import jax.numpy as jnp

    from metrics_tpu.serve.wire import encode_state

    out = {}
    for c in range(N_CLIENTS):
        cid = f"client-{c:03d}"
        rng = np.random.default_rng(7000 + c)
        coll = _factory()
        blobs = []
        for interval in range(N_INTERVALS):
            preds = jnp.asarray(rng.uniform(0, 1, SAMPLES).astype(np.float32))
            p = np.asarray(preds)
            if interval in REGRESSED:
                prob = 0.7 - 0.4 * p  # anti-correlated: interval AUROC ~ 0.3
            else:
                prob = 0.3 + 0.4 * p  # healthy: interval AUROC ~ 0.7
            target = jnp.asarray((rng.uniform(0, 1, SAMPLES) < prob).astype(np.int32))
            coll["auroc"].update(preds, target)
            coll["seen"].update(jnp.asarray(float(SAMPLES)))
            blobs.append(encode_state(coll, tenant=TENANT, client_id=cid, watermark=(0, interval)))
        out[cid] = blobs
    return out


def main() -> None:
    import tempfile
    import warnings

    import numpy as np

    from metrics_tpu import obs
    from metrics_tpu.ft import faults
    from metrics_tpu.serve import (
        Aggregator,
        AlertRule,
        GenerationFencedRangeError,
        HistoryConfig,
        HistoryRetentionError,
        Region,
        RegionalMesh,
    )
    from metrics_tpu.serve.wire import WireFormatError, peek_header

    obs.reset()
    obs.enable()
    root = tempfile.mkdtemp(prefix="history_smoke_")
    rule = AlertRule("auroc-regression", TENANT, "auroc", below=0.45, on="delta")

    def history_config():
        return HistoryConfig(cut_every_s=float("inf"), levels=LEVELS, rules=(rule,))

    agg = Aggregator("history-root", checkpoint_dir=root, history=history_config())
    agg.register_tenant(TENANT, _factory)
    snapshots = _client_snapshots()
    chaos = faults.WireChaos(
        SEED, p_drop=0.025, p_duplicate=0.025, p_reorder=0.025, p_corrupt=0.025, p_delay=0.0
    )
    delivered = set()  # (client_id, interval) delivered uncorrupted + admitted
    accepted_at_cut = {}  # interval -> {client_id: highest accepted interval}

    def deliver(blobs) -> None:
        for blob in blobs:
            try:
                _, header = peek_header(blob)
            except WireFormatError:
                continue  # framing mangled: refused before routing
            cid = str(header["client"])
            try:
                agg.ingest(blob)
            except WireFormatError:
                pass  # corrupt-in-flight: refused by the crc32
            else:
                delivered.add((cid, int(header["watermark"][1])))

    def flat_oracle(accepted):
        flat = Aggregator(f"flat-oracle-{len(accepted_at_cut)}")
        flat.register_tenant(TENANT, _factory)
        for cid, interval in sorted(accepted.items()):
            flat.ingest(snapshots[cid][interval])
        flat.flush()
        ft = flat._tenant(TENANT)
        if ft.merged_leaves is None:
            ft.fold()
        return ft

    # ---- the loadgen stream: cut rings live, kill + restore mid-way -----
    restored = False
    with warnings.catch_warnings():
        # the injected regression's one-shot FIRING warn is the point, not noise
        warnings.filterwarnings("ignore", message=".*history alert.*FIRING.*")
        for interval in range(N_INTERVALS):
            for cid in sorted(snapshots):
                _, now_blobs = chaos.plan(snapshots[cid][interval])
                deliver(now_blobs)
            deliver(chaos.end_round())
            agg.flush()
            agg.history.cut(agg, now=float(interval))
            accepted = {}
            for cid, iv in delivered:
                if cid not in accepted or iv > accepted[cid]:
                    accepted[cid] = iv
            accepted_at_cut[interval] = accepted
            if interval == KILL_AFTER:
                # checkpoint, then SIGKILL-sim: drop the root with no drain
                # and restore the ring ladder into a brand-new process image
                agg.save()
                agg = Aggregator("history-root-revived", checkpoint_dir=root,
                                 history=history_config())
                agg.register_tenant(TENANT, _factory)
                agg.restore()
                restored = True
                th = agg.history._tenants[TENANT]
                assert th.newest() is not None and th.newest().t == float(KILL_AFTER), (
                    "restore must reproduce the ladder up to the checkpointed cut"
                )
    assert restored

    # ---- ring bound enforced under sustained traffic --------------------
    th = agg.history._tenants[TENANT]
    capacity = sum(cap for _, cap in LEVELS)
    retained = th.retained()
    assert len(retained) <= capacity, (len(retained), capacity)
    assert th.evicted >= 1, "12 cuts must overflow the coarsest ring"
    assert obs.get_counter("history.intervals_evicted", tenant=TENANT) == th.evicted
    assert obs.get_counter("history.rollups", tenant=TENANT) >= 1
    assert obs.get_counter("history.cuts", tenant=TENANT) == float(N_INTERVALS)
    cut_hist = obs.get_histogram("history.cut_ms")
    assert cut_hist is not None and cut_hist.count == N_INTERVALS

    # ---- the oracle pin: every retained snapshot, bitwise ----------------
    # through ring rotation, rollup compaction and the kill+restore, each
    # retained interval must equal the flat merge of exactly the client
    # snapshots its cut had accepted
    tenant = agg._tenant(TENANT)
    spans_restore = 0
    for _, snap in retained:
        ft = flat_oracle(accepted_at_cut[int(snap.t)])
        assert tenant.spec == ft.spec
        for (path, _), ours, oracle in zip(tenant.spec, snap.leaves, ft.merged_leaves):
            assert np.array_equal(np.asarray(ours), np.asarray(oracle)), (
                f"retained interval t={snap.t} leaf {'/'.join(path)} differs"
                " from the accepted-snapshot oracle"
            )
        if snap.t <= float(KILL_AFTER):
            spans_restore += 1
    assert spans_restore >= 1, "a restored-era snapshot must survive to the end"

    # ---- range queries over the ladder ----------------------------------
    ts = [snap.t for _, snap in retained]
    res = agg.history_query(TENANT, ts[0], ts[-1], step=2.0, mode="delta")
    assert res["generation"] == 0 and len(res["intervals"]) >= 2

    def seen_between(a, b):
        acc_a, acc_b = accepted_at_cut[a], accepted_at_cut[b]
        return float(sum(
            ((acc_b[c] + 1) - (acc_a.get(c, -1) + 1)) * SAMPLES for c in acc_b
        ))

    total_seen = sum(iv["values"]["seen"]["value"] for iv in res["intervals"])
    assert total_seen == seen_between(int(ts[0]), int(ts[-1])), (
        "composed interval deltas must telescope to the endpoint delta"
    )
    for iv in res["intervals"]:
        lo, hi = iv["values"]["auroc"]["bounds"]
        assert lo <= iv["values"]["auroc"]["value"] <= hi
        assert iv["values"]["auroc"]["error_bound"] >= 0.0
    try:
        agg.history_query(TENANT, 0.0, ts[0] - 1.0)
        raise AssertionError("pre-horizon range must refuse: those intervals evicted")
    except HistoryRetentionError:
        pass
    assert obs.get_counter("history.range_queries", tenant=TENANT, mode="delta") >= 2

    # ---- the injected regression fired its rule exactly once -------------
    assert obs.get_counter("history.alerts", rule=rule.name, tenant=TENANT) == 1, (
        "two regressed intervals + recovery must count ONE edge-triggered firing"
    )
    assert agg.history.active_alerts() == [], "the rule must have cleared on recovery"
    assert obs.get_gauge("history.alert_active", rule=rule.name, tenant=TENANT) == 0.0

    # ---- every injected wire fault is visible in obs ---------------------
    for kind, count in chaos.counts.items():
        if kind in ("deliver", "reorder") or count == 0:
            continue
        assert obs.get_counter("chaos.injected", kind=kind) == count, kind

    # ---- failover arm: generation-fenced historical reads ----------------
    mesh = RegionalMesh([
        Region(name, {TENANT: _factory}, checkpoint_dir=os.path.join(root, name),
               history=HistoryConfig(cut_every_s=float("inf"), levels=((1.0, 8),)))
        for name in REGIONS
    ])
    home = {cid: REGIONS[i % len(REGIONS)] for i, cid in enumerate(sorted(snapshots))}

    def deliver_and_cut(interval: int) -> None:
        for cid in sorted(snapshots):
            mesh.region(home[cid]).ingest(snapshots[cid][interval], client_id=cid)
        mesh.replicate()
        for name in REGIONS:
            region = mesh.region(name)
            region.query_global(TENANT)  # self-ship + fold: the view is current
            region.global_view.history.cut(region.global_view, now=float(interval))

    deliver_and_cut(0)
    deliver_and_cut(1)
    for name in REGIONS:
        mesh.region(name).save()
    faults.kill_region(mesh, "west")
    promoted = faults.promote_region(mesh, "west")
    assert promoted.generation >= 1
    assert promoted.global_view.history.generation == promoted.generation, (
        "promotion must stamp the successor generation into the history tier"
    )
    gth = promoted.global_view.history._tenants[TENANT]
    assert gth.newest() is not None and gth.newest().t == 1.0, (
        "the promoted standby must restore the pre-kill ring ladder"
    )
    deliver_and_cut(2)  # the healed peers' cumulative re-ships repair the view

    # a delta range across the promotion boundary is fenced, loudly
    try:
        promoted.global_view.history_query(TENANT, 1.0, 2.0, mode="delta")
        raise AssertionError("cross-generation delta range must be fenced")
    except GenerationFencedRangeError:
        pass
    assert obs.get_counter("history.fenced_range_queries", tenant=TENANT) >= 1
    # per-generation sub-ranges and cumulative reads stay exact
    pre = promoted.global_view.history_query(TENANT, 0.0, 1.0, mode="delta")
    assert len(pre["intervals"]) == 1
    cum = promoted.global_view.history_query(TENANT, 0.0, 2.0, mode="cumulative")
    gens = [pt["snapshot"]["generation"] for pt in cum["points"]]
    assert gens[0] < promoted.generation and gens[-1] == promoted.generation, gens

    # post-heal the global range view is bitwise-equal to the flat oracle
    everyone = {cid: 2 for cid in snapshots}
    ft = flat_oracle(everyone)
    for name in REGIONS:
        gv = mesh.region(name).global_view
        newest = gv.history._tenants[TENANT].newest()
        gt = gv._tenant(TENANT)
        assert gt.spec == ft.spec
        for (path, _), ours, oracle in zip(gt.spec, newest.leaves, ft.merged_leaves):
            assert np.array_equal(np.asarray(ours), np.asarray(oracle)), (
                f"region {name} global interval leaf {'/'.join(path)} differs"
                " from the oracle after kill+promote — the re-ship must repair"
                " the range view bitwise"
            )
    assert obs.get_counter("chaos.injected", kind="region_kill") == 1
    assert obs.get_counter("chaos.injected", kind="promote") == 1

    faults_injected = sum(v for k, v in chaos.counts.items() if k != "deliver")
    print(
        f"history smoke: {N_CLIENTS} clients x {N_INTERVALS} intervals at 10% wire"
        f" faults ({faults_injected} injected) through live ring cuts, kill+restore"
        f" @ t={KILL_AFTER}, {int(th.evicted)} eviction(s) under the"
        f" {LEVELS} ladder, one edge-triggered alert firing, and a"
        f" generation-fenced failover (gen {promoted.generation}) — every retained"
        " interval bitwise-equal to the accepted-snapshot oracle",
        flush=True,
    )
    print("history smoke OK", flush=True)


if __name__ == "__main__":
    main()
