"""CI smoke: the streaming subsystem end to end on a plain CPU runner.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.streaming_smoke``
(the CI tier-1 job does, mirroring ``obs_smoke``). The cheap end-to-end
arm of the pinned unit tests in ``tests/streaming/``: a sketch-backed
metric streams within its documented error bound, the jitted
``make_stream_step`` launch emits eager-parity window values without
retracing, a drift monitor alerts through the obs counters, and a
checkpoint round-trip reproduces the value bitwise.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> None:
    import tempfile

    import metrics_tpu.obs as obs
    from metrics_tpu.ft import BatchJournal, CheckpointManager
    from metrics_tpu.steps import make_stream_step
    from metrics_tpu.streaming import DriftMonitor, StreamingAUROC, WindowedMetric

    obs.enable()
    rng = np.random.default_rng(0)
    preds = rng.uniform(0, 1, 40_000).astype(np.float32)
    target = (rng.uniform(0, 1, 40_000) < 0.25 + 0.5 * preds).astype(np.int32)

    # bounded-memory AUROC within its computable bound vs the exact answer
    m = StreamingAUROC(num_bins=1024)
    for i in range(0, 40_000, 10_000):
        m.update(jnp.asarray(preds[i : i + 10_000]), jnp.asarray(target[i : i + 10_000]))
    order = np.argsort(-preds, kind="stable")
    ranked = target[order]
    tps = np.cumsum(ranked)
    fps = np.cumsum(1 - ranked)
    trapezoid = getattr(np, "trapezoid", None) or np.trapz  # numpy<2 fallback
    exact = float(trapezoid(tps / tps[-1], fps / fps[-1]))  # exact AUROC, pure numpy
    got, bound = float(m.compute()), float(m.error_bound())
    assert abs(got - exact) <= bound + 1e-6, (got, exact, bound)
    assert m.sketch.nbytes <= 64 * 1024, m.sketch.nbytes

    # jitted stream step: eager parity per step, one trace for the loop
    eager = WindowedMetric(StreamingAUROC(num_bins=256), window=3, updates_per_slot=1)
    init, step, compute = make_stream_step(
        WindowedMetric(StreamingAUROC(num_bins=256), window=3, updates_per_slot=1)
    )
    state = init()
    for i in range(6):
        pb = jnp.asarray(preds[i * 2_000 : (i + 1) * 2_000])
        tb = jnp.asarray(target[i * 2_000 : (i + 1) * 2_000])
        eager.update(pb, tb)
        state, value = step(state, pb, tb)
        assert float(value) == float(eager.compute()), i
    label = "WindowedMetric[StreamingAUROC].stream_step"
    assert obs.get_counter("step.traces", step=label) == 1, "stream step retraced"
    assert obs.get_counter("stream.windows_expired", metric="StreamingAUROC") > 0

    # drift monitor alerts and counts
    ref = StreamingAUROC(num_bins=256)
    ref.update(jnp.asarray(preds[:10_000]), jnp.asarray(target[:10_000]))
    live = StreamingAUROC(num_bins=256)
    live.update(jnp.asarray(preds[:10_000] * 0.3), jnp.asarray(target[:10_000]))
    report = DriftMonitor(ref, psi_threshold=0.2, name="smoke", warn=False).check(live)
    assert report["alert"], report
    assert obs.get_counter("stream.drift_alerts", monitor="smoke") == 1

    # checkpoint round-trip: manifest watermark + bitwise value
    with tempfile.TemporaryDirectory() as tmp:
        mgr = CheckpointManager(os.path.join(tmp, "ck"))
        journal = BatchJournal()
        journal.record(0, 0)
        mgr.save(eager, journal=journal, epoch=0, step=0)
        resumed = WindowedMetric(StreamingAUROC(num_bins=256), window=3, updates_per_slot=1)
        j2 = BatchJournal()
        manifest = mgr.restore(resumed, journal=j2)
        assert manifest["journal"]["watermark"] == [0, 0]
        assert float(resumed.compute()) == float(eager.compute())

    print("streaming smoke OK")
    print(
        "  auroc", round(got, 6), "exact", round(exact, 6), "bound", round(bound, 6),
        "| sketch bytes", m.sketch.nbytes,
    )


if __name__ == "__main__":
    main()
