"""CI smoke: the bench regression gate end-to-end on a TINY real run.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.bench_compare_smoke``
(the CI tier-1 job does). Four arms, all through the real record builder
and the real gate (``bench.build_record`` + ``benchmarks/compare.py``):

1. a tiny-config measurement (the 500-sample compute-group A/B) becomes a
   real ``--json``-shape record and compares against the checked-in
   fixture — plumbing only, so the threshold is huge (CI runners differ
   in speed; what must work is the load/parse/normalize/report path);
2. the same record against ITSELF at the production threshold must pass;
3. an injected 2x slowdown of every row must exit nonzero;
4. a device-kind mismatch must REFUSE with exit 2, not fake-regress.

A gate that cannot fail is decoration — arms 3 and 4 are the test that it
can.
"""
import copy
import json
import os
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_compare_fixture.json")


def main() -> None:
    import bench
    from benchmarks import bench_collection
    from benchmarks.compare import (
        EXIT_OK,
        EXIT_REFUSED,
        EXIT_REGRESSED,
        CompareRefused,
        compare_records,
        load_record,
        render_report,
    )

    # --- tiny real measurement -> real record --------------------------------
    tiny = bench_collection.measure_compute_group_savings(n=500, n_classes=3, reps=1)
    rows = [
        {"metric": name, "value": round(float(ms), 3), "unit": "ms", "vs_baseline": 1.0}
        for name, ms in tiny.items()
    ]
    record = bench.build_record(rows)
    assert record["device_kind"], "record must carry a device kind"
    assert record["jax_version"] and "process_count" in record

    tmpdir = tempfile.mkdtemp(prefix="bench_compare_smoke.")
    new_path = os.path.join(tmpdir, "NEW.json")
    with open(new_path, "w") as f:
        json.dump(record, f)

    # --- arm 1: vs the checked-in fixture (plumbing; rows overlap) -----------
    old = load_record(FIXTURE)
    new = load_record(new_path)
    result = compare_records(old, new, threshold=1e9)
    report = render_report(result)
    assert result["exit_code"] == EXIT_OK, report
    overlapping = [r for r in result["rows"] if r["old_ms"] and r["new_ms"]]
    assert overlapping, "fixture and tiny run share no rows — smoke lost its teeth"
    assert "device_kind=" in report and "jax=" in report

    # --- arm 2: identical inputs pass at the production threshold ------------
    result = compare_records(new, new, threshold=1.5)
    assert result["exit_code"] == EXIT_OK, render_report(result)

    # --- arm 3: injected 2x slowdown must exit nonzero ------------------------
    slowed = copy.deepcopy(record)
    for row in slowed["rows"]:
        row["value"] *= 2.0
    slow_path = os.path.join(tmpdir, "SLOW.json")
    with open(slow_path, "w") as f:
        json.dump(slowed, f)
    result = compare_records(new, load_record(slow_path), threshold=1.5)
    assert result["exit_code"] == EXIT_REGRESSED, "a 2x slowdown sailed through the gate"
    assert result["regressions"], render_report(result)

    # --- arm 4: cross-device comparison refused -------------------------------
    foreign = copy.deepcopy(record)
    foreign["device_kind"] = "TPU v99 (smoke)"
    foreign_path = os.path.join(tmpdir, "FOREIGN.json")
    with open(foreign_path, "w") as f:
        json.dump(foreign, f)
    try:
        compare_records(new, load_record(foreign_path))
    except CompareRefused as err:
        assert "TPU v99" in str(err)
    else:
        raise AssertionError("cross-device comparison was not refused")
    assert EXIT_REFUSED == 2

    print(
        "bench compare smoke OK:",
        f"{len(overlapping)} overlapping row(s) vs fixture,",
        f"2x injection flagged {len(result['regressions'])} regression(s),",
        "cross-device refused",
    )


if __name__ == "__main__":
    main()
