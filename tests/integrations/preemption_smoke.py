"""CI smoke: real-SIGKILL preemption mid-epoch, resume, bitwise equality.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.preemption_smoke``
(the CI test job does). The in-process kill-and-resume battery
(``tests/ft/test_kill_resume.py``) injects preemptions as exceptions; this
smoke delivers the real thing: a worker subprocess streams batches through
a checkpointing eval loop and **SIGKILLs itself MID-SAVE** — the fault
harness fires a real SIGKILL at the ``checkpoint.pre_rename`` seam, after
the checkpoint is staged but before the rename publishes it. No atexit, no
finally blocks, no flushed buffers: exactly a preemption, landed in the
torn-write window. The relaunched worker resumes from the latest COMPLETE
checkpoint via the journal cursor and must finish with:

* ``compute()`` bitwise-identical to an uninterrupted in-process run
  (the mid-save batch was folded in memory but never published — it must
  be re-folded exactly once),
* an honest ``_update_count`` (every batch folded exactly once),
* the killed save's leftover ``.tmp.*`` staging dir present after the
  kill, ignored by discovery, and swept by the resumed run's saves.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_BATCHES = 24
KILL_AT = 13  # arbitrary mid-epoch batch; the worker dies before folding it
BATCH = 32


def _batches():
    import jax

    key = jax.random.PRNGKey(42)
    # noisy-mantissa floats: any drop/double-count moves bits in the mean
    return [jax.random.normal(jax.random.fold_in(key, i), (BATCH,)) * 2.345 for i in range(N_BATCHES)]


class _SigkillMidSave(BaseException):
    """Fault-injection payload that delivers a REAL SIGKILL the instant the
    'checkpoint.pre_rename' seam fires — i.e. after the checkpoint is fully
    staged but before the atomic rename publishes it. Instantiation (inside
    ``faults.maybe_fail``) is the kill, so no Python cleanup runs and the
    staging dir genuinely survives on disk."""

    def __init__(self, *args: object) -> None:
        os.kill(os.getpid(), signal.SIGKILL)


def worker(ckpt_dir: str, out_path: str, kill_at: int) -> None:
    from metrics_tpu import MeanMetric
    from metrics_tpu.ft import BatchJournal, CheckpointManager, faults

    mgr = CheckpointManager(ckpt_dir, keep_last=2)
    metric, journal = MeanMetric(), BatchJournal()
    manifest = mgr.restore(metric, journal=journal)
    print(f"worker: start folded={journal.folded} resumed={manifest is not None}", flush=True)
    for step, batch in enumerate(_batches()):
        if not journal.should_fold(0, step):
            continue
        metric.update(batch)
        journal.record(0, step)
        if step == kill_at:
            # die MID-SAVE: batch kill_at is folded in memory and staged on
            # disk, but never published — the resumed run must re-fold it
            # exactly once off the previous checkpoint, and the leftover
            # .tmp.* staging dir must be invisible to discovery
            with faults.inject("checkpoint.pre_rename", exc=_SigkillMidSave):
                mgr.save(metric, journal=journal, epoch=0, step=step)
            raise AssertionError("unreachable: SIGKILL fired mid-save")
        mgr.save(metric, journal=journal, epoch=0, step=step)
    result = {
        "value": float(metric.compute()),
        "update_count": metric._update_count,
        "folded": journal.folded,
    }
    with open(out_path, "w") as f:
        json.dump(result, f)
    print(f"worker: done {result}", flush=True)


def main() -> None:
    import numpy as np

    from metrics_tpu import MeanMetric

    reference = MeanMetric()
    for batch in _batches():
        reference.update(batch)
    expected = float(reference.compute())

    tmp = tempfile.mkdtemp(prefix="preemption_smoke.")
    ckpt_dir = os.path.join(tmp, "ckpts")
    out_path = os.path.join(tmp, "result.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run(kill_at: int) -> int:
        cmd = [sys.executable, "-m", "tests.integrations.preemption_smoke",
               "--worker", ckpt_dir, out_path, str(kill_at)]
        return subprocess.run(cmd, env=env, timeout=600).returncode

    rc = run(KILL_AT)
    assert rc == -signal.SIGKILL, f"first run should die by SIGKILL, got rc={rc}"
    assert not os.path.exists(out_path), "killed run must not have produced a result"
    assert os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir), "killed run must have checkpointed"
    leftovers = [n for n in os.listdir(ckpt_dir) if n.startswith(".tmp.")]
    assert leftovers, "SIGKILL mid-save must leave a staging dir (it fired before the rename)"

    rc = run(kill_at=-1)  # resume, no kill
    assert rc == 0, f"resumed run failed rc={rc}"
    assert not any(n.startswith(".tmp.") for n in os.listdir(ckpt_dir)), (
        "resumed run's saves must sweep the stale staging leftovers"
    )
    with open(out_path) as f:
        result = json.load(f)

    assert result["update_count"] == result["folded"] == N_BATCHES, result
    assert np.float32(result["value"]) == np.float32(expected), (
        f"kill-and-resume value {result['value']!r} != uninterrupted {expected!r} (bitwise)"
    )
    print(
        f"preemption smoke OK: SIGKILL at batch {KILL_AT}/{N_BATCHES}, resumed to"
        f" bitwise-equal compute() = {result['value']}"
    )


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker(sys.argv[2], sys.argv[3], int(sys.argv[4]))
    else:
        main()
