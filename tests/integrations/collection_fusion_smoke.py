"""CI smoke: whole-collection fusion end-to-end with observability ON.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.collection_fusion_smoke``
(the CI tier-1 job does; mirrors ``obs_smoke``). Asserts the round-7
acceptance contract cheaply: the 12-metric classification collection folds
in ONE tracked launch per epoch, members collapse to 4 update groups, the
shared input-format pass records reuse, results match the eager per-metric
loop, the fused whole-collection compute is one further launch, journal
resume trims identically, and the bench fusion rows plumb through a real
``--json``-shape record.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main() -> None:
    import metrics_tpu.obs as obs
    from metrics_tpu.ft import ResumeCursor
    from metrics_tpu.steps import make_collection_epoch

    from benchmarks.bench_collection import fusion_collection

    obs.enable()

    coll = fusion_collection(n_classes=5)
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.normal(size=(4, 64, 5)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 5, (4, 64)))

    init, epoch, compute = make_collection_epoch(coll)
    label = "MetricCollection[12].collection_epoch"
    state = init()
    for _ in range(3):
        state, _ = epoch(state, preds, target)

    # ONE tracked launch per epoch fold, one compile total, 4 update groups
    assert obs.get_counter("epoch.launches", step=label) == 3
    assert obs.get_counter("compiles", step=label) == 1
    assert obs.get_counter("runs", step=label) == 2
    assert obs.get_gauge("collection.members", step=label) == 12
    assert obs.get_gauge("collection.update_groups", step=label) == 4
    assert obs.get_counter("collection.format_reuse") > 0

    # fused whole-collection compute: one further tracked launch
    out = compute(state)
    clabel = "MetricCollection[12].collection_compute"
    assert obs.get_counter("compiles", step=clabel) + obs.get_counter("runs", step=clabel) == 1

    # eager parity (count metrics exact; float computes within jit fusion ulps)
    eager = coll.clone()
    eager.reset()
    for _ in range(3):
        for p, t in zip(preds, target):
            eager.update(p, t)
    want = eager.compute()
    assert set(out) == set(want)
    for name in out:
        got, exp = np.asarray(out[name]), np.asarray(want[name])
        if np.issubdtype(got.dtype, np.integer):
            np.testing.assert_array_equal(got, exp, err_msg=name)
        else:
            np.testing.assert_allclose(got, exp, rtol=2e-6, atol=1e-7, err_msg=name)

    # journal resume trims identically for the fused path
    resumed = init()
    resumed, _ = epoch(resumed, preds[:2], target[:2])  # pre-kill folds
    resumed, _ = epoch(resumed, preds, target, resume_from=ResumeCursor(0, 2), epoch_index=0)
    single = init()
    single, _ = epoch(single, preds, target)
    for name in single:
        for key in single[name]:
            np.testing.assert_array_equal(
                np.asarray(resumed[name][key]), np.asarray(single[name][key]), err_msg=f"{name}.{key}"
            )

    # bench fusion rows plumb through a real record (tiny config)
    import bench
    from benchmarks.bench_collection import measure_collection_fusion
    from benchmarks.compare import rows_by_metric

    tiny = measure_collection_fusion(n=2_000, n_batches=4, reps=1)
    assert tiny["collection12_launch_count"] == 1.0, tiny
    rows = [
        {
            "metric": name,
            "value": round(float(v), 3),
            "unit": "launches" if name.endswith("launch_count") else "ms",
            "vs_baseline": 1.0,
        }
        for name, v in tiny.items()
    ]
    record = bench.build_record(rows)
    parsed = rows_by_metric(record["rows"])
    assert "collection12_1M_epoch_wallclock" in parsed
    assert "collection12_launch_count" in parsed

    print(
        "collection fusion smoke OK:",
        f"{int(obs.get_gauge('collection.members', step=label))} members ->",
        f"{int(obs.get_gauge('collection.update_groups', step=label))} update groups,",
        f"{int(obs.get_counter('epoch.launches', step=label))} epoch launches,",
        f"format reuse {int(obs.get_counter('collection.format_reuse'))}",
    )


if __name__ == "__main__":
    main()
