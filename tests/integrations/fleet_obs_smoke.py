"""CI smoke: the distributed-observability plane, end to end.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.fleet_obs_smoke``
(the CI tier-1 job does). The cheap end-to-end arm of
``tests/serve/test_trace.py`` + ``tests/bases/test_obs_federation.py``:

1. an 8-client 2-level tree with obs armed — every node's hop histograms
   (queue-wait / fold / ship) are non-empty and labeled by node, the
   root's ``serve.e2e_freshness_ms`` recorded one sample per accepted
   upward payload, and the root's federated snapshot contains every
   node's counters;
2. the root's ``/trace`` route serves valid Chrome-trace JSON (loadable
   in Perfetto): host spans + one payload-lifecycle thread per trace id;
3. the chaos arm: the 10%-fault seeded loadgen's hop records account for
   EXACTLY every accepted payload, fleet-wide, and the new bench rows
   (``serve_e2e_freshness_ms`` / ``serve_hop_fold_p99_ms``) come out
   finite so the ``--json`` sweep and ``--compare`` gate have real values;
4. the zero-cost pin: an unarmed encode ships byte-identical payloads
   with no trace/obs meta.
"""
import json
import os
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import jax.numpy as jnp  # noqa: E402

TENANT = "fleet"


def factory():
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.streaming import StreamingAUROC

    return MetricCollection({"auroc": StreamingAUROC(num_bins=64)})


def client_blob(c: int, rng: np.random.Generator, step: int = 0) -> bytes:
    from metrics_tpu.serve.wire import encode_state

    coll = factory()
    preds = jnp.asarray(rng.uniform(0, 1, 64).astype(np.float32))
    target = jnp.asarray((rng.uniform(0, 1, 64) < 0.5).astype(np.int32))
    coll["auroc"].update(preds, target)
    return encode_state(coll, tenant=TENANT, client_id=f"client-{c:04d}", watermark=(0, step))


def main() -> None:
    import metrics_tpu.obs as obs
    from metrics_tpu.serve import AggregationTree, MetricsServer
    from metrics_tpu.serve.loadgen import run_loadgen
    from metrics_tpu.serve.wire import decode_state

    obs.reset()
    obs.enable()

    # -- 1: 8 clients, 2-level tree, hop provenance at every node --------
    tree = AggregationTree(fan_out=(4,), tenants={TENANT: factory})
    rng = np.random.default_rng(0)
    for c in range(8):
        tree.leaf_for(c).ingest(client_blob(c, rng))
    tree.pump()

    for node in tree.nodes:
        accepted = sum(
            node.aggregator._tenant(t).folded_payloads for t in node.aggregator.tenants()
        )
        qw = obs.get_histogram("serve.hop_queue_wait_ms", node=node.name)
        assert qw is not None and qw.count == accepted > 0, (
            f"node {node.name}: queue-wait histogram must hold one sample per"
            f" accepted payload (got {qw and qw.count} vs {accepted})"
        )
        fold = obs.get_histogram("serve.hop_fold_ms", node=node.name)
        assert fold is not None and fold.count > 0, f"node {node.name}: empty fold histogram"
    for leaf in tree.leaves:
        ship = obs.get_histogram("serve.hop_ship_ms", node=leaf.name)
        assert ship is not None and ship.count > 0, f"leaf {leaf.name}: empty ship histogram"
    fresh = obs.get_histogram("serve.e2e_freshness_ms", node="root")
    assert fresh is not None and fresh.count == 4 and fresh.min >= 0.0, fresh

    # the root's federated snapshot (local registry here — the in-process
    # tree shares one; remote snapshots merge identically, pinned by the
    # unit tests) contains every node's hop series and the fleet counters
    fed = obs.federated_snapshot()
    for node in tree.nodes:
        key = "serve.hop_queue_wait_ms{node=" + node.name + "}"
        assert key in fed["histograms"], f"federated snapshot missing {key}"
    assert fed["counters"]["serve.ingests{tenant=" + TENANT + "}"] >= 8.0

    # -- 2: /trace serves Perfetto-loadable Chrome-trace JSON ------------
    server = MetricsServer(tree.root.aggregator, port=0).start()
    try:
        raw = urllib.request.urlopen(f"http://127.0.0.1:{server.port}/trace").read()
        doc = json.loads(raw)
        events = doc["traceEvents"]
        assert isinstance(events, list) and events, "empty Chrome trace"
        for event in events:
            assert "name" in event and "ph" in event and "pid" in event, event
            if event["ph"] == "X":
                assert "ts" in event and event["dur"] >= 0.0, event
        hop_events = [e for e in events if e.get("cat") == "hop"]
        assert hop_events, "no payload-lifecycle events in /trace"
        phases = {e["name"].split("@")[0] for e in hop_events}
        assert {"queue_wait", "fold", "ship"} <= phases, phases
        # scrape self-metric: the /metrics route observes itself
        urllib.request.urlopen(f"http://127.0.0.1:{server.port}/metrics").read()
        body = urllib.request.urlopen(f"http://127.0.0.1:{server.port}/metrics").read().decode()
        assert "metrics_tpu_obs_scrape_ms_bucket" in body
    finally:
        server.stop()

    # -- 3: chaos arm + bench-row plumbing -------------------------------
    obs.reset()
    out = run_loadgen(
        n_clients=64,
        fan_out=(2, 4),
        payloads_per_client=2,
        samples_per_payload=64,
        num_bins=64,
        seed=11,
        verify=True,
        fault_rate=0.10,
    )
    assert out["verified_bitwise"] is True
    assert np.isfinite(out["serve_e2e_freshness_ms"]), out
    assert np.isfinite(out["serve_hop_fold_p99_ms"]), out
    # the family carries TWO views of the same event since the SLO plane
    # landed: the node-only series and the per-tenant variant the
    # freshness SLI differences — each must account for every accepted
    # payload exactly once (duplicates and stale replays leave no record)
    node_hops = sum(
        hist["count"]
        for key, hist in obs.histograms().items()
        if key.startswith("serve.hop_queue_wait_ms{")
        and "flat-reference" not in key
        and "tenant=" not in key
    )
    tenant_hops = sum(
        hist["count"]
        for key, hist in obs.histograms().items()
        if key.startswith("serve.hop_queue_wait_ms{")
        and "flat-reference" not in key
        and "tenant=" in key
    )
    assert node_hops == tenant_hops == out["accepted_payloads"] > 0, (
        f"hop records (node-only {node_hops}, per-tenant {tenant_hops}) must"
        f" account for every accepted payload ({out['accepted_payloads']})"
        " under 10% seeded faults"
    )

    # -- 4: zero-cost pin -------------------------------------------------
    obs.enable(False)
    blob = client_blob(99, np.random.default_rng(99))
    meta = decode_state(blob).meta
    assert "trace" not in meta and "obs_nodes" not in meta, meta
    assert blob == client_blob(99, np.random.default_rng(99)), "unarmed encode not deterministic"

    print(
        "fleet obs smoke OK: 8-client 2-level tree fully hop-attributed,"
        f" root e2e freshness p99 {fresh.p99:.2f}ms, /trace serves"
        f" {len(events)} Chrome-trace events, chaos arm accounted"
        f" {node_hops} accepted payloads at 10% faults, unarmed wire clean"
    )


if __name__ == "__main__":
    main()
