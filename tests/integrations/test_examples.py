"""Smoke-run the runnable example scripts (the reference ships runnable
``tm_examples/``; ours must stay runnable too). Each runs in its own
process so it can self-provision the virtual mesh."""
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.parametrize(
    "script",
    [
        "sharded_eval.py",
        "bootstrap_confidence.py",
        "detection_map.py",
        "train_loop_metrics.py",
        "torch_pipeline_eval.py",
        "streaming_monitor.py",
    ],
)
def test_example_runs(script):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", script)],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    assert proc.returncode == 0, f"{script} failed:\n{proc.stdout}\n{proc.stderr}"
