"""CI smoke: the sharded-state path on 8 emulated CPU devices.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.mesh_smoke`` (the CI
tier-1 job does). The cheap end-to-end arm of
``tests/bases/test_sharded_state.py``:

* sharded (reduce-scattered) sketch bins vs the replicated merge — BITWISE;
* sharded ``StreamingAUROC`` / buffer-backed ``AUROC`` values vs the eager
  oracle; ZERO materialized full-state gathers on the sharded trace,
  asserted through the ``sync.collectives`` / ``sync.payload_bytes``
  counters (only ``psum_scatter``/``psum``/ring + an n-scalar boundary
  gather);
* the ``set_collective_seam`` hook observes the hierarchical
  ICI-first/DCN-second collective order on a 2x4 mesh;
* ``make_epoch(prefetch=K)`` parity pinned bitwise against the unchunked
  launch for count- and sketch-state metrics.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402


def _shard_map(f, mesh, in_specs, out_specs):
    import metrics_tpu  # noqa: F401  — compat shims install jax.shard_map

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def main() -> None:
    import metrics_tpu.obs as obs
    from metrics_tpu import AUROC, Accuracy, make_epoch, make_step
    from metrics_tpu.streaming import ScoreLabelSketch, StreamingAUROC
    from metrics_tpu.utilities.distributed import set_collective_seam
    from metrics_tpu.utilities.sharding import shard_sketch_in_context

    assert jax.device_count() >= 8, f"need 8 emulated devices, got {jax.device_count()}"
    rng = np.random.default_rng(0)
    n = 8 * 512
    preds = jnp.asarray(rng.random(n, dtype=np.float32))
    target = jnp.asarray((rng.random(n) < 0.4).astype(np.int32))
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("dp",))

    # 1. sharded bins == replicated merge, bitwise (the monoid argument)
    template = ScoreLabelSketch(256)

    def scatter_prog(p, t):
        view = shard_sketch_in_context(template.fold(p, t), "dp")
        return view.pos, view.neg

    pos, neg = jax.jit(
        _shard_map(scatter_prog, mesh, in_specs=(P("dp"), P("dp")), out_specs=(P("dp"), P("dp")))
    )(preds, target)
    oracle = ScoreLabelSketch(256).fold(preds, target)
    assert (np.asarray(pos) == np.asarray(oracle.pos)).all(), "scattered pos bins not bitwise"
    assert (np.asarray(neg) == np.asarray(oracle.neg)).all(), "scattered neg bins not bitwise"

    # 2. sharded compute values vs eager oracle + ZERO-gather obs pin
    obs.enable()
    try:
        obs.reset()
        init, step, compute = make_step(
            StreamingAUROC(num_bins=256), axis_name="dp", with_value=False, sharded_state=True
        )

        def sk_prog(p, t):
            state, _ = step(init(), p, t)
            return compute(state)

        got = jax.jit(_shard_map(sk_prog, mesh, in_specs=(P("dp"), P("dp")), out_specs=P()))(
            preds, target
        )
        eager = StreamingAUROC(num_bins=256)
        eager.update(preds, target)
        assert abs(float(got) - float(eager.compute())) < 1e-6, (got, eager.compute())
        counters = obs.snapshot()["counters"]
        sync_keys = {k: v for k, v in counters.items() if k.startswith("sync.")}
        assert any("psum_scatter" in k for k in sync_keys), sync_keys
        big_gathers = sum(
            v
            for k, v in sync_keys.items()
            if "payload_bytes" in k and ("all_gather" in k or "buffer_gather" in k)
        )
        assert big_gathers <= 64, f"sharded path materialized a gather: {sync_keys}"

        # buffer-backed AUROC: ring pass, no gather, exact value
        obs.reset()
        cap = n // 8
        init_b, step_b, compute_b = make_step(
            AUROC(sample_capacity=cap), axis_name="dp", with_value=False, sharded_state=True
        )

        def buf_prog(p, t):
            state, _ = step_b(init_b(), p, t)
            return compute_b(state)

        got_b = jax.jit(_shard_map(buf_prog, mesh, in_specs=(P("dp"), P("dp")), out_specs=P()))(
            preds, target
        )
        exact = AUROC()
        exact.update(preds, target)
        assert abs(float(got_b) - float(exact.compute())) < 1e-6, (got_b, exact.compute())
        counters = obs.snapshot()["counters"]
        assert any("ring_permute" in k for k in counters), counters
        assert not any("buffer_gather" in k for k in counters), counters

        # 3. seam observes the hierarchical ICI-then-DCN collective order
        mesh2 = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4), ("dcn", "ici"))
        init_h, step_h, compute_h = make_step(
            Accuracy(num_classes=5),
            axis_name=("ici", "dcn"),
            with_value=False,
            hierarchical_sync=True,
        )
        pc = jnp.asarray(rng.integers(0, 5, n))
        tc = jnp.asarray(rng.integers(0, 5, n))

        def h_prog(p, t):
            state, _ = step_h(init_h(), p, t)
            return compute_h(state)

        seen: list = []
        prev = set_collective_seam(lambda x, op, ax: (seen.append((op, ax)), x)[1])
        try:
            got_h = jax.jit(
                _shard_map(h_prog, mesh2, in_specs=(P(("dcn", "ici")), P(("dcn", "ici"))), out_specs=P())
            )(pc, tc)
        finally:
            set_collective_seam(prev)
        assert abs(float(got_h) - float((np.asarray(pc) == np.asarray(tc)).mean())) < 1e-6
        axes = [ax for _op, ax in seen]
        assert "ici" in axes and "dcn" in axes, seen
        for i, ax in enumerate(axes):
            if ax == "dcn":
                assert axes[i - 1] == "ici", f"DCN hop not preceded by its ICI hop: {seen}"
    finally:
        obs.reset()
        obs.enable(False)

    # 4. prefetch parity: chunked double-buffered fold bitwise vs monolithic
    pe = np.asarray(rng.integers(0, 5, (16, 64)))
    te = np.asarray(rng.integers(0, 5, (16, 64)))
    init0, epoch0, compute0 = make_epoch(Accuracy, num_classes=5)
    initk, epochk, computek = make_epoch(Accuracy, num_classes=5, prefetch=4)
    s0, _ = epoch0(init0(), jnp.asarray(pe), jnp.asarray(te))
    sk, _ = epochk(initk(), pe, te)
    for name in s0:
        assert (np.asarray(s0[name]) == np.asarray(sk[name])).all(), name
    assert float(compute0(s0)) == float(computek(sk))

    rng2 = np.random.default_rng(1)
    pe2 = rng2.random((12, 128), dtype=np.float32)
    te2 = (rng2.random((12, 128)) < 0.5).astype(np.int32)
    initS, epochS, _ = make_epoch(StreamingAUROC(num_bins=128))
    initP, epochP, _ = make_epoch(StreamingAUROC(num_bins=128), prefetch=5)
    sS, _ = epochS(initS(), jnp.asarray(pe2), jnp.asarray(te2))
    sP, _ = epochP(initP(), pe2, te2)
    assert (np.asarray(sS["sketch"].pos) == np.asarray(sP["sketch"].pos)).all()
    assert (np.asarray(sS["sketch"].neg) == np.asarray(sP["sketch"].neg)).all()

    print(
        "mesh smoke OK: scattered bins bitwise, sharded AUROC/sketch values exact,"
        " zero materialized gathers, ICI-then-DCN seam order, prefetch parity pinned"
    )


if __name__ == "__main__":
    main()
