"""CI smoke: the serving tier end to end, through a REAL SIGKILL.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.serve_smoke``
(the CI test job does, mirroring ``obs_smoke``/``streaming_smoke``). The
cheap end-to-end arm of ``tests/serve/``:

* a 2-level :class:`~metrics_tpu.serve.AggregationTree` (root + leaf
  aggregators) boots in-process and ingests from 8 simulated clients —
  every payload delivered TWICE and half the clients' intervals delivered
  OUT OF ORDER (at-least-once delivery, hostile network);
* the worker subprocess checkpoints the root and **SIGKILLs itself
  mid-stream** (after the save, with undelivered payloads in flight — a
  real preemption, no atexit/finally cleanup);
* the relaunch restores the root BITWISE (verified against a flat offline
  merge of the pre-kill snapshots), rebuilds the interior nodes from
  their children's re-ships (the resumed ship sequence must clear the
  restored watermarks), and finishes the stream;
* the final ``/query`` answer over HTTP matches a single flat offline
  merge of each client's LAST snapshot exactly once — BITWISE on every
  state leaf — and the ``/metrics`` scrape parses line by line as
  Prometheus text exposition.
"""
import json
import os
import signal
import subprocess
import sys
import tempfile
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_CLIENTS = 8
N_INTERVALS = 3
SAMPLES = 96
TENANT = "smoke"
FAN_OUT = (3,)  # 2-level tree: 1 root + 3 leaf aggregators


def _factory():
    from metrics_tpu import MaxMetric, SumMetric
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.streaming import StreamingAUROC

    return MetricCollection(
        {"auroc": StreamingAUROC(num_bins=128), "seen": SumMetric(), "peak": MaxMetric()}
    )


def _client_snapshots():
    """Deterministic cumulative snapshots: ``{client_id: [bytes per
    interval]}`` — identical bytes in every process that calls this, which
    is what lets the killed worker and the verifying parent agree."""
    import numpy as np

    import jax.numpy as jnp

    from metrics_tpu.serve.wire import encode_state

    out = {}
    for c in range(N_CLIENTS):
        cid = f"client-{c:02d}"
        rng = np.random.default_rng(1000 + c)
        coll = _factory()
        blobs = []
        for interval in range(N_INTERVALS):
            preds = jnp.asarray(rng.uniform(0, 1, SAMPLES).astype(np.float32))
            target = jnp.asarray((rng.uniform(0, 1, SAMPLES) < 0.3 + 0.4 * np.asarray(preds)).astype(np.int32))
            coll["auroc"].update(preds, target)
            coll["seen"].update(jnp.asarray(float(SAMPLES)))
            coll["peak"].update(preds)
            blobs.append(encode_state(coll, tenant=TENANT, client_id=cid, watermark=(0, interval)))
        out[cid] = blobs
    return out


def _deliver(tree, snapshots, upto_interval: int) -> None:
    """At-least-once hostile delivery: every snapshot twice, intervals
    reversed for odd clients."""
    for c, (cid, blobs) in enumerate(sorted(snapshots.items())):
        order = blobs[: upto_interval + 1]
        if c % 2 == 1:
            order = list(reversed(order))
        for blob in order:
            tree.leaf_for(c).ingest(blob)
            tree.leaf_for(c).ingest(blob)  # duplicate delivery


def _flat_leaves(snapshots, interval: int):
    """Reference: one flat aggregator folding each client's snapshot at
    ``interval`` exactly once. Returns (spec, numpy leaves)."""
    import numpy as np

    from metrics_tpu.serve import Aggregator

    flat = Aggregator("flat-reference")
    flat.register_tenant(TENANT, _factory)
    for cid, blobs in snapshots.items():
        flat.ingest(blobs[interval])
    flat.flush()
    t = flat._tenant(TENANT)
    if t.merged_leaves is None:
        t.fold()
    return t.spec, [np.asarray(x) for x in t.merged_leaves]


def _root_leaves(tree):
    import numpy as np

    tree.root.aggregator.flush()
    t = tree.root.aggregator._tenant(TENANT)
    if t.merged_leaves is None:
        t.fold()
    return t.spec, [np.asarray(x) for x in t.merged_leaves]


def _assert_bitwise(spec, ours, reference, label: str) -> None:
    import numpy as np

    for (path, _), a, b in zip(spec, ours, reference):
        assert a.dtype == b.dtype and a.shape == b.shape, (label, path)
        assert np.array_equal(a, b, equal_nan=True), (
            f"{label}: leaf {'/'.join(path)} differs from the flat offline merge"
        )


def worker(ckpt_root: str) -> None:
    """Ingest intervals 0..1, checkpoint the root, SIGKILL mid-stream."""
    from metrics_tpu.serve import AggregationTree

    snapshots = _client_snapshots()
    tree = AggregationTree(fan_out=FAN_OUT, tenants={TENANT: _factory}, checkpoint_root=ckpt_root)
    _deliver(tree, snapshots, upto_interval=1)
    tree.pump(rounds=2)
    tree.save()
    # interval-2 payloads land in leaf queues but are NEVER pumped or
    # checkpointed — in-flight work a preemption genuinely loses; the
    # at-least-once redelivery after restore must recover it
    _deliver(tree, snapshots, upto_interval=2)
    print("worker: checkpointed through interval 1, dying mid-stream", flush=True)
    os.kill(os.getpid(), signal.SIGKILL)


def main() -> None:
    from metrics_tpu import obs
    from metrics_tpu.serve import AggregationTree, MetricsServer

    tmp = tempfile.mkdtemp(prefix="serve_smoke.")
    ckpt_root = os.path.join(tmp, "root-ckpts")

    rc = subprocess.run(
        [sys.executable, "-m", "tests.integrations.serve_smoke", "--worker", ckpt_root],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=600,
    ).returncode
    assert rc == -signal.SIGKILL, f"worker should die by SIGKILL, got rc={rc}"
    assert os.path.isdir(ckpt_root) and os.listdir(ckpt_root), "worker must have checkpointed"

    snapshots = _client_snapshots()
    obs.enable()

    # relaunch: restore the root, interior nodes rebuild from re-ships
    tree = AggregationTree(fan_out=FAN_OUT, tenants={TENANT: _factory}, checkpoint_root=ckpt_root)
    manifest = tree.restore()
    assert manifest is not None, "restore() found no checkpoint"

    # the restored root state IS the pre-kill state: bitwise equal to a
    # flat offline merge of every client's interval-1 snapshot
    spec, restored = _root_leaves(tree)
    flat_spec, flat_pre = _flat_leaves(snapshots, interval=1)
    assert spec == flat_spec
    _assert_bitwise(spec, restored, flat_pre, "restored root")
    print("serve smoke: SIGKILL-restore bitwise vs flat merge of pre-kill snapshots OK", flush=True)

    # finish the stream: hostile redelivery of EVERYTHING (dups included),
    # several pump rounds so re-ships clear the restored watermarks
    _deliver(tree, snapshots, upto_interval=2)
    tree.pump(rounds=3)
    spec, final = _root_leaves(tree)
    _, flat_final = _flat_leaves(snapshots, interval=2)
    _assert_bitwise(spec, final, flat_final, "final root")
    drops = obs.sum_counter("serve.dedup_drops")
    assert drops > 0, "duplicate/out-of-order deliveries must be dropped, not re-merged"

    # HTTP surface over the restored root: /query matches the flat offline
    # merge through JSON, /metrics parses as Prometheus exposition
    server = MetricsServer(tree.root.aggregator, port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        q = json.load(urllib.request.urlopen(f"{base}/query?tenant={TENANT}", timeout=10))
        # the root's clients are its leaf-node identities, not end clients
        assert q["clients"] == len(tree.leaves)
        offline = tree.root.aggregator.query(TENANT)
        assert q == json.loads(json.dumps(offline)), "HTTP /query != in-process query"
        auroc = q["values"]["auroc"]
        assert auroc["bounds"][0] <= auroc["value"] <= auroc["bounds"][1]
        assert q["values"]["seen"]["value"] == float(N_CLIENTS * N_INTERVALS * SAMPLES)

        body = urllib.request.urlopen(f"{base}/metrics", timeout=10).read().decode()
        families = set()
        for line in body.splitlines():
            if line.startswith("# TYPE"):
                families.add(line.split()[2])
                continue
            if not line or line.startswith("#"):
                continue
            name = line.split("{")[0].split(" ")[0]
            float(line.rsplit(" ", 1)[1])  # every sample line parses
            assert name.startswith("metrics_tpu_"), line
        for family in (
            "metrics_tpu_serve_ingests",
            "metrics_tpu_serve_merges",
            "metrics_tpu_serve_dedup_drops",
            "metrics_tpu_serve_value",
            "metrics_tpu_serve_ingest_ms",
        ):
            assert family in families, f"scrape missing family {family}"
        health = json.load(urllib.request.urlopen(f"{base}/healthz", timeout=10))
        assert health["tenants"] == 1
    finally:
        server.stop()

    print(
        f"serve smoke OK: {N_CLIENTS} clients x {N_INTERVALS} intervals through a"
        f" {len(FAN_OUT) + 1}-level tree, duplicated + reordered + SIGKILL-restored,"
        f" final query bitwise-equal to the flat offline merge"
        f" ({int(drops)} hostile deliveries dropped)"
    )


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker(sys.argv[2])
    else:
        main()
