"""Metric lifecycle inside a training loop (reference
``integrations/test_lightning.py``): per-step forward values, per-epoch
compute/reset, and accumulation-matches-oracle over the epoch — without the
Lightning dependency, driving the same log/accumulate/reset semantics from a
plain jitted loop."""
import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu import Accuracy, MeanMetric, MetricCollection, SumMetric


def test_epoch_accumulate_reset_semantics():
    rng = np.random.default_rng(0)
    acc = Accuracy()
    n_batches, batch = 4, 32
    for epoch in range(2):
        all_p, all_t = [], []
        for _ in range(n_batches):
            p = jnp.asarray(rng.uniform(0, 1, batch))
            t = jnp.asarray(rng.integers(0, 2, batch))
            step_val = acc(p, t)
            # step value is batch-local
            ref_step = ((np.asarray(p) >= 0.5).astype(int) == np.asarray(t)).mean()
            np.testing.assert_allclose(float(step_val), ref_step, atol=1e-6)
            all_p.append(np.asarray(p))
            all_t.append(np.asarray(t))
        epoch_val = acc.compute()
        ref_epoch = ((np.concatenate(all_p) >= 0.5).astype(int) == np.concatenate(all_t)).mean()
        np.testing.assert_allclose(float(epoch_val), ref_epoch, atol=1e-6)
        acc.reset()
        # state is cleared between epochs
        assert int(acc.tp) == 0 and int(acc.fn) == 0


def test_collection_in_jitted_loop():
    """Metrics consume outputs of a jitted step without retracing per batch."""
    trace_count = 0

    @jax.jit
    def step(w, x):
        nonlocal trace_count
        trace_count += 1
        return jax.nn.sigmoid(x @ w)

    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(8,)), dtype=jnp.float32)
    metrics = MetricCollection([Accuracy()], prefix="train/")
    for _ in range(5):
        x = jnp.asarray(rng.normal(size=(16, 8)), dtype=jnp.float32)
        t = jnp.asarray(rng.integers(0, 2, 16))
        metrics(step(w, x), t)
    assert trace_count == 1, "jitted step must not retrace across batches"
    out = metrics.compute()
    assert set(out) == {"train/Accuracy"}


def test_logged_aggregators_track_loss():
    """MeanMetric/SumMetric as loss trackers (Lightning's self.log analogue)."""
    mean_loss, total_seen = MeanMetric(), SumMetric()
    losses = [0.9, 0.7, 0.5, 0.3]
    for loss in losses:
        mean_loss.update(loss)
        total_seen.update(1)
    np.testing.assert_allclose(float(mean_loss.compute()), np.mean(losses), atol=1e-6)
    assert int(total_seen.compute()) == len(losses)
