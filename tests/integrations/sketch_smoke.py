"""CI smoke: the sketch trio survives the hostile fleet, bitwise.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.sketch_smoke``
(the CI step does, mirroring ``elastic_smoke``). 1000 clients ship two
cumulative snapshot intervals of heavy-hitter / distinct-count /
co-occurrence state through an elastic :class:`~metrics_tpu.serve.
AggregationTree`, consulting the consistent-hash Router per ship, under
a seeded 10% :class:`~metrics_tpu.ft.faults.WireChaos` schedule (drop /
duplicate / reorder / corrupt / delay). Between intervals a node JOINS
and an intermediate is HARD-KILLED and rebuilt by the Supervisor.

Acceptance, all asserted here:

* the final root merged state is **bitwise-equal to the flat oracle
  merge of exactly the accepted snapshots** — linear-sketch merges are
  exact integer-valued sums (HLL registers an idempotent max), so chaos
  duplicates, reordering, and topology churn must be invisible;
* the root's answers carry **rigorous envelopes vs exact references**
  computed directly from the accepted samples: every reported heavy
  hitter's true count lies inside ``bounds()``, the exact top item is
  reported, the distinct estimate lands within 3 sigma of the true
  unique count, and every reported co-occurrence cell's bound interval
  contains the exact pair count;
* the HTTP ``/query`` surface agrees with the in-process query.
"""
import collections
import json
import os
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

SEED = 20260806
N_CLIENTS = 1000
N_INTERVALS = 2
SAMPLES = 64
TENANT = "sketch"
FAN_OUT = (2, 4)
ID_SPACE = 2000
LABELS = 200


def _factory():
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.streaming import (
        StreamingConfusion,
        StreamingDistinctCount,
        StreamingTopK,
    )

    return MetricCollection(
        {
            "topk": StreamingTopK(k=8, capacity=256, depth=4, id_bits=20),
            "uniq": StreamingDistinctCount(precision=12),
            "conf": StreamingConfusion(num_rows=LABELS, k=8, capacity=256, depth=4),
        }
    )


def _client_data():
    """Per-client per-interval id batches (numpy, also the exact oracle's
    raw material)."""
    import numpy as np

    data = {}
    for c in range(N_CLIENTS):
        rng = np.random.default_rng(9000 + c)
        data[f"client-{c:04d}"] = [
            (rng.zipf(1.3, SAMPLES) % ID_SPACE).astype(np.int32)
            for _ in range(N_INTERVALS)
        ]
    return data


def _client_snapshots(data):
    import jax.numpy as jnp

    from metrics_tpu.serve.wire import encode_state

    out = {}
    for cid, batches in data.items():
        coll = _factory()
        blobs = []
        for interval, batch in enumerate(batches):
            ids = jnp.asarray(batch)
            coll["topk"].update(ids)
            coll["uniq"].update(ids)
            coll["conf"].update(ids % LABELS, (ids * 7) % LABELS)
            blobs.append(
                encode_state(coll, tenant=TENANT, client_id=cid, watermark=(0, interval))
            )
        out[cid] = blobs
    return out


def main() -> None:
    import numpy as np

    from metrics_tpu import obs
    from metrics_tpu.ft import faults
    from metrics_tpu.serve import (
        AggregationTree,
        Aggregator,
        ElasticFleet,
        MetricsServer,
        ResilienceConfig,
        Supervisor,
    )
    from metrics_tpu.serve.wire import WireFormatError, peek_header

    obs.reset()
    obs.enable()
    data = _client_data()
    snapshots = _client_snapshots(data)
    chaos = faults.WireChaos(
        SEED, p_drop=0.02, p_duplicate=0.02, p_reorder=0.02, p_corrupt=0.02, p_delay=0.02
    )
    tree = AggregationTree(
        fan_out=FAN_OUT,
        tenants={TENANT: _factory},
        resilience=ResilienceConfig(error_threshold=3),
    )
    fleet = ElasticFleet(tree, seed=SEED)
    supervisor = Supervisor(tree, heartbeat_timeout_s=5.0, name="supervisor", warn=False)

    delivered = set()  # (client_id, interval) delivered uncorrupted + admitted

    def deliver(blobs) -> None:
        for blob in blobs:
            try:
                _, header = peek_header(blob)
            except WireFormatError:
                continue  # corruption mangled the framing: refused anywhere
            cid = str(header["client"])
            try:
                fleet.router.route(cid).ingest(blob)  # router consulted PER SHIP
            except WireFormatError:
                pass  # corrupt-in-flight: refused by the crc32
            else:
                delivered.add((cid, int(header["watermark"][1])))

    def deliver_interval(interval: int) -> None:
        for cid in sorted(snapshots):
            _, now_blobs = chaos.plan(snapshots[cid][interval])
            deliver(now_blobs)
        deliver(chaos.end_round())

    # interval 0, then a node JOINS (ring re-homing under live traffic)
    deliver_interval(0)
    fleet.pump()
    joined = faults.join_node(fleet)
    assert joined.name in fleet.router.members()

    # interval 1, then an intermediate HARD-KILL + supervised rebuild
    deliver_interval(1)
    fleet.pump()
    kill_victim = chaos.choice(tree.levels[1])
    faults.kill_node(kill_victim)
    assert "dead_node" in {f["kind"] for f in supervisor.check()["findings"]}
    actions = supervisor.heal()
    assert any(a["action"] == "rebuild_node" and a["node"] == kill_victim.name for a in actions)
    deliver(chaos.flush())
    fleet.pump(rounds=3)

    # ---- oracle: flat merge of exactly the accepted snapshots -----------
    accepted = {}
    for cid, interval in delivered:
        if cid not in accepted or interval > accepted[cid]:
            accepted[cid] = interval
    assert len(accepted) > 0.8 * N_CLIENTS  # 10% chaos cannot eat the fleet
    flat = Aggregator("flat-oracle")
    flat.register_tenant(TENANT, _factory)
    for cid, interval in sorted(accepted.items()):
        flat.ingest(snapshots[cid][interval])
    flat.flush()
    flat_tenant = flat._tenant(TENANT)
    if flat_tenant.merged_leaves is None:
        flat_tenant.fold()
    tree.root.aggregator.flush()
    root_tenant = tree.root.aggregator._tenant(TENANT)
    if root_tenant.merged_leaves is None:
        root_tenant.fold()
    assert root_tenant.spec == flat_tenant.spec
    for (path, _), ours, oracle in zip(
        root_tenant.spec, root_tenant.merged_leaves, flat_tenant.merged_leaves
    ):
        assert np.array_equal(np.asarray(ours), np.asarray(oracle)), (
            f"root leaf {'/'.join(path)} differs from the accepted-snapshot oracle"
            " after join + intermediate-kill churn at 10% wire faults"
        )

    # ---- envelopes vs EXACT references from the accepted samples --------
    exact = collections.Counter()
    exact_cells = collections.Counter()
    for cid, interval in sorted(accepted.items()):
        for batch in data[cid][: interval + 1]:
            for i in batch.tolist():
                exact[i] += 1
                exact_cells[(i % LABELS, (i * 7) % LABELS)] += 1
    exact_uniques = len(exact)

    view = tree.root.aggregator.collection(TENANT)
    ids, counts = (np.asarray(a) for a in view["topk"].compute())
    lo, hi = (np.asarray(a) for a in view["topk"].bounds())
    reported = [int(i) for i in ids if i >= 0]
    assert len(reported) == 8
    for slot, item in enumerate(ids.tolist()):
        if item < 0:
            continue
        true = exact[item]
        assert lo[slot] <= true <= hi[slot], (
            f"heavy hitter {item}: true count {true} outside [{lo[slot]}, {hi[slot]}]"
        )
    true_top = exact.most_common(1)[0][0]
    assert true_top in reported, f"exact top item {true_top} missing from reported top-k"

    est = float(view["uniq"].compute())
    sigma = float(view["uniq"].error_bound())  # relative error, 1.04/sqrt(m)
    assert abs(est - exact_uniques) <= 3.0 * sigma * exact_uniques, (
        f"distinct estimate {est} vs exact {exact_uniques} beyond 3 sigma"
    )

    rows, cols, cell_counts = (np.asarray(a) for a in view["conf"].compute())
    import jax.numpy as jnp

    clo, chi = (
        np.asarray(a)
        for a in view["conf"].cell_bounds(jnp.asarray(rows), jnp.asarray(cols))
    )
    for slot, (r, c) in enumerate(zip(rows.tolist(), cols.tolist())):
        if r < 0:
            continue
        true = exact_cells[(r, c)]
        assert clo[slot] <= true <= chi[slot], (
            f"cell ({r},{c}): true {true} outside [{clo[slot]}, {chi[slot]}]"
        )

    # ---- the HTTP surface agrees ----------------------------------------
    server = MetricsServer(tree.root.aggregator, port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        q = json.load(urllib.request.urlopen(f"{base}/query?tenant={TENANT}", timeout=10))
        offline = tree.root.aggregator.query(TENANT)
        assert q == json.loads(json.dumps(offline)), "HTTP /query != in-process query"
    finally:
        server.stop()

    faults_injected = sum(v for k, v in chaos.counts.items() if k != "deliver")
    print(
        f"sketch smoke: {len(accepted)}/{N_CLIENTS} clients accepted x {N_INTERVALS}"
        f" intervals at 10% wire faults ({faults_injected} injected) through"
        f" join({joined.name}) + hard-kill({kill_victim.name}) + supervised rebuild —"
        f" root bitwise-equal to the flat oracle; top-{len(reported)} envelopes, distinct"
        f" ({est:.0f} vs exact {exact_uniques}), and co-occurrence cell bounds all hold"
        " against the exact references",
        flush=True,
    )
    print("sketch smoke OK", flush=True)


if __name__ == "__main__":
    main()
