"""CI smoke: LLM-eval tenants and the decision engine under fleet chaos.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.experiment_smoke``
(the CI step does, mirroring ``history_smoke``). A two-arm online
experiment — 500 clients per arm — plus an LLM-eval tenant
(perplexity / token-F1 / RAG quality) ship cumulative snapshots through
an elastic :class:`~metrics_tpu.serve.AggregationTree` under a seeded
10% :class:`~metrics_tpu.ft.faults.WireChaos` schedule, with a node
JOIN and an intermediate HARD-KILL + supervised heal mid-run. The tree
root forwards its merged state to a history-armed DECISION root where a
:class:`~metrics_tpu.experiment.DecisionEngine` evaluates on every cut.

Acceptance, all asserted here:

* the injected true effect fires **exactly one** SHIP decision
  (edge-triggered, counted once under ``experiment.decisions``) — and it
  fires AFTER the decision root was checkpointed, hard-killed and
  restored, so the always-valid p-value demonstrably continues from
  durable state;
* the null experiment **never** fires across the seeded run (the
  type-I spot check riding the same traffic);
* the decision root's final records are **bitwise-equal** to an
  uninterrupted reference run fed the identical forwarded payloads —
  kill-resume is invisible to decisions;
* the LLM tenant's root state is **bitwise-equal to the flat oracle
  merge of exactly the accepted snapshots**, at the tree root AND at
  the restored decision root (sum/sketch monoid states survive chaos
  duplicates, elastic churn, and kill-resume exactly).
"""
import json
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")

SEED = 20260807
N_PER_ARM = 500
N_LLM_CLIENTS = 100
N_INTERVALS = 4
SAMPLES = 8  # latency samples per client per interval
KILL_AFTER = 1  # checkpoint + kill + restore the decision root after this cut
FAN_OUT = (2, 4)
EXP_TRUE = "checkout-latency"
EXP_NULL = "null-check"
LLM_TENANT = "llm-eval"
# min_samples so the true effect cannot decide before cut 2 — i.e. only
# AFTER the kill+restore at cut 1 (cumulative per-arm n at cut k is
# roughly 500 * 8 * (k+1) minus chaos losses)
MIN_SAMPLES = 10_000


def _lat_factory():
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.streaming import StreamingQuantile

    return MetricCollection({"lat": StreamingQuantile(num_bins=128, lo=0.0, hi=1.0)})


def _llm_factory():
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.llm import StreamingPerplexity, StreamingRAGQuality, StreamingTokenF1

    return MetricCollection(
        {
            "ppl": StreamingPerplexity(),
            "f1": StreamingTokenF1(),
            "rag": StreamingRAGQuality(k=4, num_bins=64),
        }
    )


def _experiments():
    from metrics_tpu.experiment import ArmSpec, Experiment, SequentialTest

    true_exp = Experiment(
        EXP_TRUE,
        arms=[ArmSpec("control", _lat_factory), ArmSpec("treatment", _lat_factory)],
        metric="lat",
        test=SequentialTest(alpha=0.05, tau=0.1, min_samples=MIN_SAMPLES, family="mean"),
        higher_is_better=False,  # latency: lower is better -> ship
    )
    null_exp = Experiment(
        EXP_NULL,
        arms=[ArmSpec("control", _lat_factory), ArmSpec("treatment", _lat_factory)],
        metric="lat",
        test=SequentialTest(alpha=0.05, tau=0.1, min_samples=MIN_SAMPLES, family="mean"),
        higher_is_better=False,
    )
    return true_exp, null_exp


def _arm_tenants():
    true_exp, null_exp = _experiments()
    return {tid: _lat_factory for exp in (true_exp, null_exp) for tid in exp.tenant_ids()}


def _client_snapshots():
    """Pre-encode every client's cumulative wire blobs, per tenant."""
    import numpy as np

    import jax.numpy as jnp

    from metrics_tpu.serve.wire import encode_state

    # arm traffic: treatment of the TRUE experiment is genuinely faster;
    # every other arm draws the same latency distribution
    shifts = {
        f"{EXP_TRUE}/control": 0.0,
        f"{EXP_TRUE}/treatment": -0.10,
        f"{EXP_NULL}/control": 0.0,
        f"{EXP_NULL}/treatment": 0.0,
    }
    out = {}
    for tid, shift in shifts.items():
        for c in range(N_PER_ARM):
            cid = f"{tid}:c{c:03d}"
            rng = np.random.default_rng(abs(hash(tid)) % 100_000 + c)
            coll = _lat_factory()
            blobs = []
            for interval in range(N_INTERVALS):
                vals = np.clip(rng.normal(0.5 + shift, 0.05, SAMPLES), 0.0, 1.0)
                coll["lat"].update(jnp.asarray(vals.astype(np.float32)))
                blobs.append(
                    encode_state(coll, tenant=tid, client_id=cid, watermark=(0, interval))
                )
            out[cid] = (tid, blobs)
    for c in range(N_LLM_CLIENTS):
        cid = f"llm:c{c:03d}"
        rng = np.random.default_rng(50_000 + c)
        coll = _llm_factory()
        blobs = []
        for interval in range(N_INTERVALS):
            # quantized to the 2^-10 dyadic grid: every partial sum of
            # log_prob_sum is then exactly representable in float32
            # (|total| * 1024 << 2^24), so the tree-shaped fold at the
            # root is BITWISE the flat oracle fold regardless of the
            # association order the elastic topology happens to produce
            lp = np.round(np.log(rng.uniform(0.1, 1.0, 32)) * 1024.0) / 1024.0
            lp = lp.astype(np.float32)
            coll["ppl"].update(jnp.asarray(lp), num_bytes=64)
            pred = f"answer {rng.integers(0, 4)}"
            gold = f"answer {rng.integers(0, 4)}"
            coll["f1"].update([pred], [gold])
            scores = rng.permutation(16).astype(np.float32)
            rel = (rng.uniform(size=16) < 0.3).astype(np.int32)
            idx = np.repeat(np.arange(4), 4)
            coll["rag"].update(jnp.asarray(scores), jnp.asarray(rel), jnp.asarray(idx))
            blobs.append(
                encode_state(coll, tenant=LLM_TENANT, client_id=cid, watermark=(0, interval))
            )
        out[cid] = (LLM_TENANT, blobs)
    return out


def main() -> None:
    import tempfile
    import warnings

    import numpy as np

    from metrics_tpu import obs
    from metrics_tpu.experiment import DecisionEngine
    from metrics_tpu.ft import faults
    from metrics_tpu.serve import (
        AggregationTree,
        Aggregator,
        ElasticFleet,
        HistoryConfig,
        ResilienceConfig,
        Supervisor,
    )
    from metrics_tpu.serve.wire import WireFormatError, encode_state, peek_header

    obs.reset()
    obs.enable()
    root_dir = tempfile.mkdtemp(prefix="experiment_smoke_")
    tenants = dict(_arm_tenants())
    tenants[LLM_TENANT] = _llm_factory
    snapshots = _client_snapshots()
    chaos = faults.WireChaos(
        SEED, p_drop=0.025, p_duplicate=0.025, p_reorder=0.025, p_corrupt=0.025, p_delay=0.0
    )
    tree = AggregationTree(
        fan_out=FAN_OUT, tenants=tenants, resilience=ResilienceConfig(error_threshold=3)
    )
    fleet = ElasticFleet(tree, seed=SEED)
    supervisor = Supervisor(tree, heartbeat_timeout_s=5.0, name="supervisor", warn=False)

    def build_decision_root(name):
        agg = Aggregator(
            name,
            checkpoint_dir=root_dir,
            history=HistoryConfig(cut_every_s=float("inf")),
        )
        for tid, fac in tenants.items():
            agg.register_tenant(tid, fac)
        engine = DecisionEngine(agg, list(_experiments()))
        return agg, engine

    decision_root, engine = build_decision_root("decision-root")
    delivered = set()  # (client_id, interval) accepted into the tree

    def deliver(blobs) -> None:
        for blob in blobs:
            try:
                _, header = peek_header(blob)
            except WireFormatError:
                continue  # framing mangled: refused before routing
            cid = str(header["client"])
            try:
                fleet.router.route(cid).ingest(blob)  # router consulted PER SHIP
            except WireFormatError:
                pass  # corrupt-in-flight: refused by the crc32
            else:
                delivered.add((cid, int(header["watermark"][1])))

    # ---- the loadgen stream through the elastic tree --------------------
    forwarded = []  # per interval: the tree-root -> decision-root payloads
    restored = False
    joined = kill_victim = None
    with warnings.catch_warnings():
        # the true experiment's one-shot DECIDED warn is the point, not noise
        warnings.filterwarnings("ignore", message=".*DECIDED.*")
        for interval in range(N_INTERVALS):
            for cid in sorted(snapshots):
                _, now_blobs = chaos.plan(snapshots[cid][1][interval])
                deliver(now_blobs)
            deliver(chaos.end_round())
            if interval == 0:  # elastic churn arc: JOIN under live traffic
                fleet.pump()
                joined = faults.join_node(fleet)
                assert joined.name in fleet.router.members()
            if interval == 2:  # intermediate HARD-KILL + supervised heal
                fleet.pump()
                kill_victim = chaos.choice(tree.levels[1])
                faults.kill_node(kill_victim)
                assert "dead_node" in {f["kind"] for f in supervisor.check()["findings"]}
                actions = supervisor.heal()
                assert any(
                    a["action"] == "rebuild_node" and a["node"] == kill_victim.name
                    for a in actions
                )
                deliver(chaos.flush())
            fleet.pump(rounds=3)
            # forward the tree root's merged cumulative state to the
            # history-armed decision root, one payload per tenant
            tree.root.aggregator.flush()
            ships = [
                encode_state(
                    tree.root.aggregator.collection(tid),
                    tenant=tid,
                    client_id="tree-root",
                    watermark=(0, interval),
                )
                for tid in sorted(tenants)
            ]
            forwarded.append(ships)
            for blob in ships:
                decision_root.ingest(blob)
            decision_root.flush()
            decision_root.history.cut(decision_root, now=float(interval))
            if interval == KILL_AFTER:
                # checkpoint, then SIGKILL-sim: drop the decision root with
                # no drain; a fresh root + engine restores (attach-before-
                # restore) and keeps deciding from the durable p-value
                decision_root.save()
                decision_root, engine = build_decision_root("decision-root-revived")
                decision_root.restore()
                restored = True
                assert engine.report(EXP_TRUE)["verdict"] == "continue", (
                    "min_samples must hold the verdict until after the restore"
                )
    assert restored and joined is not None and kill_victim is not None

    # ---- exactly one ship, fired AFTER the kill+restore ------------------
    rec = engine.report(EXP_TRUE)
    assert rec["verdict"] == "ship", rec
    assert rec["decision"]["cut"]["control"] > KILL_AFTER, (
        "the decision must postdate the restore — otherwise this run never"
        " exercised post-restore continuation"
    )
    assert obs.get_counter("experiment.decisions", exp=EXP_TRUE, verdict="ship") == 1
    assert obs.get_gauge("experiment.active", exp=EXP_TRUE) == 0.0
    null_rec = engine.report(EXP_NULL)
    assert null_rec["verdict"] == "continue", null_rec
    assert null_rec["evaluations"] >= 1
    assert obs.get_counter("experiment.decisions", exp=EXP_NULL, verdict="ship") == 0
    assert obs.get_counter("experiment.decisions", exp=EXP_NULL, verdict="stop") == 0

    # ---- kill-resume bitwise: an uninterrupted reference run -------------
    ref_dir = tempfile.mkdtemp(prefix="experiment_smoke_ref_")
    ref = Aggregator("reference-root", checkpoint_dir=ref_dir,
                     history=HistoryConfig(cut_every_s=float("inf")))
    for tid, fac in tenants.items():
        ref.register_tenant(tid, fac)
    ref_engine = DecisionEngine(ref, list(_experiments()))
    with warnings.catch_warnings():
        warnings.filterwarnings("ignore", message=".*DECIDED.*")
        for interval, ships in enumerate(forwarded):
            for blob in ships:
                ref.ingest(blob)
            ref.flush()
            ref.history.cut(ref, now=float(interval))
    assert json.dumps(engine.state_for_checkpoint(), sort_keys=True) == json.dumps(
        ref_engine.state_for_checkpoint(), sort_keys=True
    ), "kill-resume must be invisible to the decision records, bitwise"

    # ---- LLM tenant: bitwise flat oracle at tree root AND decision root --
    accepted = {}
    for cid, interval in delivered:
        if snapshots[cid][0] == LLM_TENANT and (cid not in accepted or interval > accepted[cid]):
            accepted[cid] = interval
    assert len(accepted) > 0.8 * N_LLM_CLIENTS  # 10% chaos cannot eat the fleet
    flat = Aggregator("flat-oracle")
    flat.register_tenant(LLM_TENANT, _llm_factory)
    for cid, interval in sorted(accepted.items()):
        flat.ingest(snapshots[cid][1][interval])
    flat.flush()
    flat_tenant = flat._tenant(LLM_TENANT)
    if flat_tenant.merged_leaves is None:
        flat_tenant.fold()
    for label, agg in (("tree root", tree.root.aggregator), ("decision root", decision_root)):
        t = agg._tenant(LLM_TENANT)
        if t.merged_leaves is None:
            t.fold()
        assert t.spec == flat_tenant.spec
        for (path, _), ours, oracle in zip(t.spec, t.merged_leaves, flat_tenant.merged_leaves):
            assert np.array_equal(np.asarray(ours), np.asarray(oracle)), (
                f"{label} LLM leaf {'/'.join(path)} differs from the"
                " accepted-snapshot oracle after elastic churn + kill-resume"
            )
    view = decision_root.collection(LLM_TENANT)
    assert float(view["ppl"].compute()) > 1.0
    hit, mrr, ndcg = (float(x) for x in view["rag"].compute())
    assert 0.0 <= mrr <= 1.0 and 0.0 <= ndcg <= 1.0 and 0.0 <= hit <= 1.0

    faults_injected = sum(v for k, v in chaos.counts.items() if k != "deliver")
    n_clients = 4 * N_PER_ARM + N_LLM_CLIENTS
    print(
        f"experiment smoke: {n_clients} clients x {N_INTERVALS} intervals at 10% wire"
        f" faults ({faults_injected} injected) through join({joined.name}) +"
        f" hard-kill({kill_victim.name}) + heal, decision root kill+restore @"
        f" t={KILL_AFTER} — one post-restore SHIP (p={rec['decision']['p_value']:.3g}),"
        f" null continue (p={null_rec['p_value']:.3g}), records bitwise vs the"
        " uninterrupted reference, LLM root states bitwise vs the flat oracle",
        flush=True,
    )
    print("experiment smoke OK", flush=True)


if __name__ == "__main__":
    main()
