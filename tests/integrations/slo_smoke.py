"""CI smoke: the tenant-facing SLO plane under fleet chaos.

Run as ``JAX_PLATFORMS=cpu python -m tests.integrations.slo_smoke``
(the CI step does, mirroring ``experiment_smoke``). Three tenants ship
cumulative snapshots through an elastic
:class:`~metrics_tpu.serve.AggregationTree` under a seeded 10%
:class:`~metrics_tpu.ft.faults.WireChaos` schedule, with a node JOIN
and an intermediate HARD-KILL + supervised heal mid-run. The tree root
forwards its merged state to a history-armed, firewall-armed SLO root
where a :class:`~metrics_tpu.obs.slo.SLOEngine` evaluates per-tenant
error budgets on every cut and a
:class:`~metrics_tpu.obs.prober.CanaryProber` round-trips known-answer
payloads through the real ingest path.

Acceptance, all asserted here:

* one tenant (``gamma``) suffers an injected two-interval wire-error
  flood: its burn-rate alert fires **exactly once** (edge-triggered,
  one ``slo.alerts`` increment, one ``SLO BURN`` warning) and clears
  after the flood ages out of both windows;
* the healthy tenants never alert and keep (near-)full error budgets
  riding the SAME 10% chaos traffic;
* the canary stays green through the fleet kill+heal AND the SLO root's
  own checkpoint kill+restore (the prober rebinds, keeping its oracle);
* the budget table survives the checkpoint kill+restore **bitwise**
  (the revived engine's state equals the pre-kill state exactly);
* ``GET /slo`` and ``GET /tenants`` parse and match in-process state.
"""
import json
import os
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

SEED = 20260807
TENANTS = ("alpha", "beta", "gamma")
FLOOD_TENANT = "gamma"
N_CLIENTS = 30  # per tenant
N_INTERVALS = 6
FAN_OUT = (2, 4)
CUT_SPACING_S = 100.0
FLOOD_INTERVALS = (2, 3)
FLOOD_ERRORS = 150  # corrupt blobs per flood interval
KILL_AFTER = 3  # checkpoint + kill + restore the SLO root after this cut


def _factory():
    from metrics_tpu.aggregation import SumMetric
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.streaming import StreamingQuantile

    return MetricCollection(
        {"seen": SumMetric(), "lat": StreamingQuantile(num_bins=64, lo=0.0, hi=1.0)}
    )


def _slos():
    """Window/burn parameters matched to the manual cut cadence: cuts
    land CUT_SPACING_S apart, so the fast window sees one cut's delta
    and the slow window roughly two — a two-interval flood trips both
    rules at its first cut and ages out two cuts after it stops."""
    from metrics_tpu.obs.slo import SLODef

    return [
        SLODef(
            "ingest",
            sli="ingest_success",
            objective=0.9,
            fast_window_s=60.0,
            slow_window_s=240.0,
            fast_burn=3.0,
            slow_burn=2.0,
        ),
        SLODef("freshness", sli="freshness", objective=0.5, threshold_ms=60_000.0),
        SLODef("canary", sli="canary", objective=0.999),
    ]


def _client_snapshots():
    import numpy as np

    import jax.numpy as jnp

    from metrics_tpu.serve.wire import encode_state

    out = {}
    for tid in TENANTS:
        for c in range(N_CLIENTS):
            cid = f"{tid}:c{c:03d}"
            rng = np.random.default_rng(abs(hash(tid)) % 100_000 + c)
            coll = _factory()
            blobs = []
            for interval in range(N_INTERVALS):
                vals = np.clip(rng.normal(0.5, 0.1, 16), 0.0, 1.0).astype(np.float32)
                coll["seen"].update(jnp.asarray(float(len(vals))))
                coll["lat"].update(jnp.asarray(vals))
                blobs.append(
                    encode_state(coll, tenant=tid, client_id=cid, watermark=(0, interval))
                )
            out[cid] = (tid, blobs)
    return out


def _corrupt_blobs(interval: int) -> list:
    """FLOOD_ERRORS wire blobs for the flood tenant with valid framing
    but a flipped payload byte: the header parses (so the error is
    ATTRIBUTED to the tenant) and the crc32 refuses the body (so each
    counts one ``slo.ingest_errors{reason=wire}``). Distinct spoofed
    client ids keep any single identity under the firewall's circuit
    threshold — this is a tenant-level burn, not one bad client."""
    import jax.numpy as jnp

    from metrics_tpu.serve.wire import encode_state

    blobs = []
    for i in range(FLOOD_ERRORS):
        coll = _factory()
        coll["seen"].update(jnp.asarray(1.0))
        blob = bytearray(
            encode_state(
                coll,
                tenant=FLOOD_TENANT,
                client_id=f"ghost-{interval}-{i:03d}",
                watermark=(0, 0),
            )
        )
        blob[-3] ^= 0xFF
        blobs.append(bytes(blob))
    return blobs


def _get_json(port: int, path: str):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as resp:
        return json.loads(resp.read().decode("utf-8"))


def main() -> None:
    import tempfile
    import warnings

    from metrics_tpu import obs
    from metrics_tpu.ft import faults
    from metrics_tpu.obs.prober import CANARY_TENANT, CanaryProber, canary_metrics
    from metrics_tpu.obs.slo import SLOEngine
    from metrics_tpu.serve import (
        AggregationTree,
        Aggregator,
        ElasticFleet,
        HistoryConfig,
        MetricsServer,
        ResilienceConfig,
        Supervisor,
    )
    from metrics_tpu.serve.wire import WireFormatError, encode_state, peek_header

    obs.reset()
    obs.enable()
    root_dir = tempfile.mkdtemp(prefix="slo_smoke_")
    tenants = {tid: _factory for tid in TENANTS}
    snapshots = _client_snapshots()
    chaos = faults.WireChaos(
        SEED, p_drop=0.025, p_duplicate=0.025, p_reorder=0.025, p_corrupt=0.025, p_delay=0.0
    )
    tree = AggregationTree(
        fan_out=FAN_OUT, tenants=tenants, resilience=ResilienceConfig(error_threshold=3)
    )
    fleet = ElasticFleet(tree, seed=SEED)
    supervisor = Supervisor(tree, heartbeat_timeout_s=5.0, name="supervisor", warn=False)

    def build_slo_root(name):
        agg = Aggregator(
            name,
            checkpoint_dir=root_dir,
            history=HistoryConfig(cut_every_s=float("inf")),
            resilience=True,  # the firewall seam attributes wire errors per tenant
        )
        for tid, fac in tenants.items():
            agg.register_tenant(tid, fac)
        agg.register_tenant(CANARY_TENANT, canary_metrics)
        engine = SLOEngine(agg, slos=_slos())
        return agg, engine

    slo_root, engine = build_slo_root("slo-root")
    prober = CanaryProber(slo_root)

    def deliver(blobs) -> None:
        for blob in blobs:
            try:
                _, header = peek_header(blob)
            except WireFormatError:
                continue  # framing mangled: refused before routing
            cid = str(header["client"])
            try:
                fleet.router.route(cid).ingest(blob)  # router consulted PER SHIP
            except WireFormatError:
                pass  # corrupt-in-flight: refused by the crc32

    restored = False
    joined = kill_victim = None
    wire_errors_injected = 0
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for interval in range(N_INTERVALS):
            for cid in sorted(snapshots):
                _, now_blobs = chaos.plan(snapshots[cid][1][interval])
                deliver(now_blobs)
            deliver(chaos.end_round())
            if interval == 0:  # elastic churn arc: JOIN under live traffic
                fleet.pump()
                joined = faults.join_node(fleet)
                assert joined.name in fleet.router.members()
            if interval == 2:  # intermediate HARD-KILL + supervised heal
                fleet.pump()
                kill_victim = chaos.choice(tree.levels[1])
                faults.kill_node(kill_victim)
                assert "dead_node" in {f["kind"] for f in supervisor.check()["findings"]}
                actions = supervisor.heal()
                assert any(
                    a["action"] == "rebuild_node" and a["node"] == kill_victim.name
                    for a in actions
                )
                deliver(chaos.flush())
            fleet.pump(rounds=3)
            tree.root.aggregator.flush()
            for tid in sorted(tenants):
                slo_root.ingest(
                    encode_state(
                        tree.root.aggregator.collection(tid),
                        tenant=tid,
                        client_id="tree-root",
                        watermark=(0, interval),
                    )
                )
            if interval in FLOOD_INTERVALS:
                for bad in _corrupt_blobs(interval):
                    try:
                        slo_root.ingest(bad)
                    except WireFormatError:
                        wire_errors_injected += 1
            # the canary rides the same ingest path every interval —
            # through the fleet kill+heal AND the SLO root's kill+restore
            assert prober.probe() == "match", prober.status()
            slo_root.flush()
            slo_root.history.cut(slo_root, now=interval * CUT_SPACING_S)
            if interval == FLOOD_INTERVALS[0]:
                rec = engine.budget(FLOOD_TENANT, "ingest")
                assert rec is not None and rec.firing and rec.alerts == 1, (
                    "the flood must trip the dual-window rule at its first cut"
                )
            if interval == KILL_AFTER:
                # checkpoint, then SIGKILL-sim: drop the SLO root with no
                # drain; a fresh root + engine restores (attach-before-
                # restore) and the prober REBINDS, keeping its oracle
                want_state = json.dumps(engine.state_for_checkpoint(), sort_keys=True)
                slo_root.save()
                slo_root, engine = build_slo_root("slo-root-revived")
                slo_root.restore()
                prober.rebind(slo_root)
                restored = True
                got_state = json.dumps(engine.state_for_checkpoint(), sort_keys=True)
                assert got_state == want_state, (
                    "the budget table must survive checkpoint kill+restore bitwise"
                )
                assert engine.budget(FLOOD_TENANT, "ingest").firing, (
                    "the restored record must still be firing — no duplicate edge"
                )
    assert restored and joined is not None and kill_victim is not None
    assert wire_errors_injected == FLOOD_ERRORS * len(FLOOD_INTERVALS)

    # ---- exactly one alert, edge-triggered, recovered --------------------
    burns = [w for w in caught if "SLO BURN" in str(w.message)]
    assert len(burns) == 1, [str(w.message) for w in burns]
    rec = engine.budget(FLOOD_TENANT, "ingest")
    assert rec.alerts == 1, rec.to_dict()
    assert rec.firing is False, "the flood must age out of both windows by the last cut"
    assert obs.get_counter("slo.alerts", tenant=FLOOD_TENANT, slo="ingest") == 1
    assert obs.get_gauge("slo.alert_active", tenant=FLOOD_TENANT, slo="ingest") == 0.0
    assert engine.active_alerts() == []

    # ---- healthy tenants unaffected --------------------------------------
    flood_remaining = rec.budget_remaining(
        (N_INTERVALS - 1) * CUT_SPACING_S, engine._slos["ingest"]
    )
    for tid in TENANTS:
        if tid == FLOOD_TENANT:
            continue
        healthy = engine.budget(tid, "ingest")
        assert healthy is not None and healthy.alerts == 0 and not healthy.firing
        remaining = healthy.budget_remaining(
            (N_INTERVALS - 1) * CUT_SPACING_S, engine._slos["ingest"]
        )
        assert remaining > 0.7, (tid, remaining)
        assert remaining > flood_remaining, (
            "the flood tenant must have burned visibly more budget than the"
            " healthy tenants riding the same chaos"
        )
        assert obs.get_counter("slo.alerts", tenant=tid, slo="ingest") == 0

    # ---- canary green end to end -----------------------------------------
    status = prober.status()
    assert status["healthy"] is True and status["mismatches"] == 0
    assert status["matches"] == N_INTERVALS
    canary_rec = engine.budget(CANARY_TENANT, "canary")
    assert canary_rec is not None and canary_rec.bad == 0.0

    # ---- /slo and /tenants parse and match in-process state --------------
    server = MetricsServer(slo_root, port=0, arm_obs=False).start()
    try:
        slo_body = _get_json(server.port, "/slo")
        assert slo_body == json.loads(json.dumps(server.render_slo())), (
            "GET /slo must match the in-process report"
        )
        assert set(slo_body["slos"]) == {"ingest", "freshness", "canary"}
        assert slo_body["tenants"][FLOOD_TENANT]["ingest"]["alerts"] == 1
        assert slo_body["active_alerts"] == []
        tenants_body = _get_json(server.port, "/tenants")
        assert set(tenants_body["tenants"]) >= set(TENANTS) | {CANARY_TENANT}
        for tid in TENANTS:
            assert tenants_body["tenants"][tid]["wire_bytes"] > 0
        ranked = {row["tenant"] for row in tenants_body["top_consumers"]}
        assert set(TENANTS) <= ranked
        ready = _get_json(server.port, "/healthz/ready")
        assert ready["canary"]["healthy"] is True
        assert ready["slo_alerts"] == []
    finally:
        server.stop()

    faults_injected = sum(v for k, v in chaos.counts.items() if k != "deliver")
    print(
        f"slo smoke: {len(TENANTS) * N_CLIENTS} clients x {N_INTERVALS} intervals at"
        f" 10% wire faults ({faults_injected} injected) through join({joined.name}) +"
        f" hard-kill({kill_victim.name}) + heal, SLO root kill+restore @"
        f" t={KILL_AFTER} — {FLOOD_TENANT} alert fired exactly once and recovered,"
        f" healthy budgets intact, canary {status['matches']}/{N_INTERVALS} green,"
        " budgets bitwise across restore, /slo + /tenants consistent",
        flush=True,
    )
    print("slo smoke OK", flush=True)


if __name__ == "__main__":
    main()
