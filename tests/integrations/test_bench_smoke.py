"""Bench-harness smoke tests.

The driver runs ``bench.py`` unattended at the end of every round; a
wiring error there (bad import, renamed key, signature drift) silently
costs the round its numbers. These tests import every bench module and
run the parameterizable measure functions at tiny configs — they assert
plumbing, not performance.
"""
import importlib

import numpy as np
import pytest


@pytest.mark.parametrize(
    "module",
    [
        "bench",
        "benchmarks._timing",
        "benchmarks.bench_collection",
        "benchmarks.bench_curves",
        "benchmarks.bench_detection",
        "benchmarks.bench_image",
        "benchmarks.bench_retrieval",
        "benchmarks.bench_sync",
        "benchmarks.bench_text_image",
        "benchmarks.map_oracle",
    ],
)
def test_bench_module_imports(module):
    importlib.import_module(module)


def test_detection_measure_tiny():
    from benchmarks import bench_detection

    ms = bench_detection.measure(n_images=20, n_trials=1)
    assert np.isfinite(ms) and ms > 0


def test_ssim_measure_tiny():
    from benchmarks import bench_image

    out = bench_image.measure_ssim(batch=2, side=32, k=2)
    (key,) = out.keys()
    assert key == "ssim_2x3x32x32_compute"
    assert np.isfinite(out[key]) and out[key] > 0


def test_wer_measure_tiny():
    from benchmarks import bench_text_image

    ms = bench_text_image.measure_wer(n_pairs=50)
    assert np.isfinite(ms) and ms > 0
    preds, targets = bench_text_image.wer_corpus(50)
    assert len(preds) == len(targets) == 50


def test_compute_group_savings_tiny():
    from benchmarks import bench_collection

    out = bench_collection.measure_compute_group_savings(n=500, n_classes=3, reps=1)
    assert set(out) == {
        "collection_prf1_500_update_groups_on",
        "collection_prf1_500_update_groups_off",
    }
    assert all(np.isfinite(v) and v > 0 for v in out.values())
