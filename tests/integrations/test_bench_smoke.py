"""Bench-harness smoke tests.

The driver runs ``bench.py`` unattended at the end of every round; a
wiring error there (bad import, renamed key, signature drift) silently
costs the round its numbers. These tests import every bench module and
run the parameterizable measure functions at tiny configs — they assert
plumbing, not performance.
"""
import importlib

import numpy as np
import pytest


@pytest.mark.parametrize(
    "module",
    [
        "bench",
        "benchmarks._timing",
        "benchmarks.bench_collection",
        "benchmarks.bench_curves",
        "benchmarks.bench_detection",
        "benchmarks.bench_image",
        "benchmarks.bench_retrieval",
        "benchmarks.bench_sync",
        "benchmarks.bench_text_image",
        "benchmarks.map_oracle",
    ],
)
def test_bench_module_imports(module):
    importlib.import_module(module)


def test_streaming_measure_tiny():
    import bench

    out = bench.bench_streaming(n=4_096)
    assert set(out) == {
        "streaming_auroc_1M_update",
        "streaming_auroc_1M_merge",
        "streaming_auroc_1M_compute",
        "windowed_fold_k16",
    }
    assert all(np.isfinite(v) and v > 0 for v in out.values())


def test_detection_measure_tiny():
    from benchmarks import bench_detection

    ms = bench_detection.measure(n_images=20, n_trials=1)
    assert np.isfinite(ms) and ms > 0


def test_ssim_measure_tiny():
    from benchmarks import bench_image

    out = bench_image.measure_ssim(batch=2, side=32, k=2)
    (key,) = out.keys()
    assert key == "ssim_2x3x32x32_compute"
    assert np.isfinite(out[key]) and out[key] > 0


def test_wer_measure_tiny():
    from benchmarks import bench_text_image

    ms = bench_text_image.measure_wer(n_pairs=50)
    assert np.isfinite(ms) and ms > 0
    # split reporting: the published value is HOST kernel time; the device
    # round trip rides along as its own field
    assert hasattr(ms, "tunnel_rtt_ms") and ms.tunnel_rtt_ms >= 0
    preds, targets = bench_text_image.wer_corpus(50)
    assert len(preds) == len(targets) == 50


def test_retrieval_topk_bench_kernel_tiny():
    from benchmarks import bench_retrieval

    saved = (bench_retrieval.N_QUERIES, bench_retrieval.DOCS, bench_retrieval.K, bench_retrieval.K_TOPK)
    try:
        bench_retrieval.N_QUERIES, bench_retrieval.DOCS = 20, 30
        bench_retrieval.K, bench_retrieval.K_TOPK = 2, 2
        bench_retrieval.N = 20 * 30
        out = bench_retrieval.measure()
    finally:
        (bench_retrieval.N_QUERIES, bench_retrieval.DOCS, bench_retrieval.K, bench_retrieval.K_TOPK) = saved
        bench_retrieval.N = bench_retrieval.N_QUERIES * bench_retrieval.DOCS
    assert "retrieval_map_k10_1M_docs_compute" in out
    assert all(np.isfinite(v) and v > 0 for v in out.values())


def test_cluster_direct_samples_protocol():
    """Direct-sample clustering: a lone fast sample must NOT anchor the
    published median (ADVICE round-5 low #3); two agreeing fast samples do."""
    from benchmarks._timing import cluster_direct_samples

    # lone minimum, rest 10x slower: publish the overall median, no split
    lone = cluster_direct_samples([10.0, 100.0, 101.0, 102.0, 103.0])
    assert lone.slow_mode_median is None
    assert lone.fast_mode_median == 101.0  # overall median
    # two agreeing fast samples: min-anchored fast/slow split as before
    agreeing = cluster_direct_samples([10.0, 11.0, 100.0, 101.0, 102.0])
    assert agreeing.fast_mode_median == 10.5
    assert agreeing.slow_mode_median == 101.0
    assert (agreeing.n_fast, agreeing.n_slow) == (2, 3)
    # degenerate inputs
    assert cluster_direct_samples([]) is None
    single = cluster_direct_samples([42.0])
    assert float(single) == 42.0


def test_compute_group_savings_tiny():
    from benchmarks import bench_collection

    out = bench_collection.measure_compute_group_savings(n=500, n_classes=3, reps=1)
    assert set(out) == {
        "collection_prf1_500_update_groups_on",
        "collection_prf1_500_update_groups_off",
    }
    assert all(np.isfinite(v) and v > 0 for v in out.values())


def test_bench_json_record(tmp_path):
    """--json record: schema, device metadata, rows survive a round trip."""
    import json

    import bench

    path = tmp_path / "BENCH_test.json"
    rows = [
        {"metric": "demo", "value": 1.5, "unit": "ms", "vs_baseline": 2.0, "section_compile_s": 0.25}
    ]
    bench.write_json_record(str(path), rows)
    rec = json.loads(path.read_text())
    assert rec["schema"] == 1
    assert rec["rows"] == rows
    for key in ("device_kind", "platform", "jax_version", "device_count", "recorded_unix"):
        assert key in rec, key
    assert set(rec["obs"]) == {"compile_listener_installed", "jax_compile_seconds", "jax_compiles"}


def test_bench_json_flag_in_cli_surface():
    """bench.py's CLI accepts --json PATH (the driver calls it blind)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    out = subprocess.run(
        [sys.executable, "bench.py", "--help"],
        capture_output=True, text=True, timeout=120, cwd=repo,
    )
    assert out.returncode == 0, out.stderr
    assert "--json" in out.stdout
