"""Streaming metrics inside a jitted JAX/optax training loop.

Equivalent of the reference's Lightning integration
(``integrations/lightning.py`` + ``integrations/test_lightning.py``): the
reference logs ``metric.forward`` per step and ``metric.compute`` per epoch
from ``LightningModule`` hooks. The idiomatic JAX version shown here keeps
the *gradient step* jitted and pure, then drives a ``MetricCollection``
with the step's outputs — ``collection(preds, target)`` returns batch-local
values (step logging), ``collection.compute()`` the epoch aggregate, and
``collection.reset()`` starts the next epoch.

Run: ``python examples/train_loop_metrics.py``
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo-root run without install

import jax
import jax.numpy as jnp
import numpy as np
import optax

from metrics_tpu import Accuracy, F1Score, MetricCollection, Precision, Recall


def make_data(n: int = 512, d: int = 16, seed: int = 0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(d,))
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (x @ w_true > 0).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


@jax.jit
def loss_fn(w, x, y):
    logits = x @ w
    return optax.sigmoid_binary_cross_entropy(logits, y.astype(jnp.float32)).mean()


@jax.jit
def train_step(w, opt_state, x, y):
    loss, grads = jax.value_and_grad(loss_fn)(w, x, y)
    updates, opt_state = optimizer.update(grads, opt_state)
    w = optax.apply_updates(w, updates)
    return w, opt_state, loss, jax.nn.sigmoid(x @ w)


if __name__ == "__main__":
    x, y = make_data()
    w = jnp.zeros((x.shape[1],))
    optimizer = optax.adam(1e-1)
    opt_state = optimizer.init(w)

    metrics = MetricCollection(
        [Accuracy(), Precision(), Recall(), F1Score()], prefix="train/"
    )

    batch = 64
    for epoch in range(3):
        for i in range(0, len(x), batch):
            xb, yb = x[i : i + batch], y[i : i + batch]
            w, opt_state, loss, probs = train_step(w, opt_state, xb, yb)
            step_values = metrics(probs, yb)  # batch-local, Lightning on_step logging
        epoch_values = metrics.compute()  # epoch aggregate, on_epoch logging
        print(f"epoch {epoch}: " + ", ".join(f"{k}={float(v):.3f}" for k, v in epoch_values.items()))
        metrics.reset()
