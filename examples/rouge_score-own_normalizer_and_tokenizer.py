"""ROUGE with a user-supplied normalizer and tokenizer.

Equivalent of the reference example
``tm_examples/rouge_score-own_normalizer_and_tokenizer.py``: shows how the
``normalizer``/``tokenizer`` hooks of :class:`metrics_tpu.ROUGEScore` replace
the built-in lowercase/alphanumeric normalization and whitespace split —
e.g. for languages or domains where the defaults are wrong.

Run: ``python examples/rouge_score-own_normalizer_and_tokenizer.py``
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo-root run without install

import re
from pprint import pprint
from typing import Sequence

from metrics_tpu import ROUGEScore


class UserNormalizer:
    """Keep digits as words too (the default normalizer strips punctuation only)."""

    def __init__(self) -> None:
        self.pattern = re.compile(r"[^a-z0-9]+")

    def __call__(self, text: str) -> str:
        return self.pattern.sub(" ", text.lower())


class UserTokenizer:
    """Split on whitespace; a real use-case would plug a subword/char tokenizer."""

    pattern = re.compile(r"\s+")

    def __call__(self, text: str) -> Sequence[str]:
        return self.pattern.split(text)


if __name__ == "__main__":
    preds = "My name is John".split(". ")
    target = "Is your name John".split(". ")

    rouge = ROUGEScore(normalizer=UserNormalizer(), tokenizer=UserTokenizer())
    for p, t in zip(preds, target):
        rouge.update(p, t)
    pprint({k: float(v) for k, v in rouge.compute().items()})
