"""Bootstrap confidence intervals as ONE compiled sharded program.

The reference's ``BootStrapper`` keeps N deep copies of a metric and pays N
eager updates per batch; here the replicate axis lives INSIDE the step
carry (``make_step(BootStrapper(...))``), so a whole bootstrapped
evaluation — resampling, N replicate updates, mesh sync, and the
mean/std/quantile statistics — compiles into a single XLA program:
``lax.scan`` over batches, ``shard_map`` over a data-parallel mesh, one
``(B, N)`` in-trace ``jax.random`` resample matrix per step.

Works anywhere: provisions an 8-device virtual CPU mesh when no multi-chip
backend is initialized, exactly like the test suite.
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo-root run without install

import jax

try:  # self-provision a virtual mesh when the backend allows it
    from jax._src import xla_bridge

    if not xla_bridge.backends_are_initialized():
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import Accuracy, make_step
from metrics_tpu.wrappers import BootStrapper

N_DEV = min(8, jax.device_count())
N_BATCHES, BATCH, N_CLASSES, N_BOOT = 12, 32 * N_DEV, 5, 50

mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("dp",))

rng = np.random.default_rng(0)
logits_ok = rng.integers(0, N_CLASSES, (N_BATCHES, BATCH))
target = np.where(
    rng.uniform(size=(N_BATCHES, BATCH)) < 0.7, logits_ok, rng.integers(0, N_CLASSES, (N_BATCHES, BATCH))
)
preds = jnp.asarray(logits_ok)
target = jnp.asarray(target)

boot = BootStrapper(
    Accuracy(num_classes=N_CLASSES),
    num_bootstraps=N_BOOT,
    seed=42,
    sampling_strategy="multinomial",
    quantile=jnp.asarray([0.025, 0.975]),
)
init, step, compute = make_step(boot, axis_name="dp")


def epoch(p, t):
    """One device's shard: scan the batches, then mesh-synced statistics."""
    carry0 = jax.lax.pcast(init(), ("dp",), to="varying")  # scan carries are device-varying
    carry, _ = jax.lax.scan(lambda s, b: step(s, *b), carry0, (p, t))
    return compute(carry)


stats = jax.jit(
    jax.shard_map(
        epoch,
        mesh=mesh,
        in_specs=(P(None, "dp"), P(None, "dp")),
        out_specs=P(),
    )
)(preds, target)

point = (np.asarray(preds) == np.asarray(target)).mean()
lo, hi = np.asarray(stats["quantile"])
print(f"accuracy          : {point:.4f}")
print(f"bootstrap mean    : {float(stats['mean']):.4f}")
print(f"bootstrap std     : {float(stats['std']):.4f}")
print(f"95% CI            : [{lo:.4f}, {hi:.4f}]")
assert lo <= point <= hi, "point estimate should fall inside the bootstrap CI"
