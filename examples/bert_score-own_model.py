"""BERTScore with a user-supplied model, tokenizer and forward function.

Equivalent of the reference example ``tm_examples/bert_score-own_model.py``:
instead of a ``transformers`` checkpoint, a toy character-level "encoder"
(here a fixed random embedding table + mixing matrix in jnp) is plugged in
via the ``model`` / ``user_tokenizer`` / ``user_forward_fn`` hooks, showing
the contract each hook must satisfy:

* tokenizer: ``(List[str], max_length) -> {"input_ids", "attention_mask"}``
  (numpy/jnp int arrays, padded to a common length)
* forward_fn: ``(model, batch_dict) -> [batch, seq_len, model_dim]`` array

Run: ``python examples/bert_score-own_model.py``
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo-root run without install

from pprint import pprint
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional import bert_score

_MAX_LEN = 32
_VOCAB = 128
_DIM = 16


class CharTokenizer:
    """Byte-level tokenizer: one token per character, padded to max length."""

    def __call__(self, sentences: List[str], max_length: int = _MAX_LEN) -> Dict[str, np.ndarray]:
        ids = np.zeros((len(sentences), max_length), dtype=np.int32)
        mask = np.zeros((len(sentences), max_length), dtype=np.int32)
        for i, sentence in enumerate(sentences):
            tokens = [min(ord(c), _VOCAB - 1) for c in sentence[:max_length]]
            ids[i, : len(tokens)] = tokens
            mask[i, : len(tokens)] = 1
        return {"input_ids": ids, "attention_mask": mask}


class ToyEncoder:
    """Embedding table + one dense mixing layer; stands in for a Flax encoder."""

    def __init__(self) -> None:
        k1, k2 = jax.random.split(jax.random.PRNGKey(42))
        self.embed = jax.random.normal(k1, (_VOCAB, _DIM))
        self.mix = jax.random.normal(k2, (_DIM, _DIM)) / jnp.sqrt(_DIM)


def forward_fn(model: ToyEncoder, batch: Dict[str, np.ndarray]) -> jnp.ndarray:
    ids = jnp.asarray(batch["input_ids"])
    mask = jnp.asarray(batch["attention_mask"])[..., None]
    return (model.embed[ids] @ model.mix) * mask


if __name__ == "__main__":
    preds = ["hello there", "general kenobi"]
    target = ["hello there", "master kenobi"]
    score = bert_score(
        preds,
        target,
        model=ToyEncoder(),
        user_tokenizer=CharTokenizer(),
        user_forward_fn=forward_fn,
        max_length=_MAX_LEN,
    )
    pprint({k: [round(float(x), 4) for x in v] for k, v in score.items()})
