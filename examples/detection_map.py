"""COCO mAP on a small hand-built scene.

Equivalent of the reference example ``tm_examples/detection_map.py``: one
image, several predicted boxes with scores/labels vs ground-truth boxes,
printing the full COCO summary dict.

Run: ``python examples/detection_map.py``
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo-root run without install

from pprint import pprint

import jax.numpy as jnp

from metrics_tpu import MeanAveragePrecision

if __name__ == "__main__":
    preds = [
        dict(
            boxes=jnp.asarray([[258.0, 41.0, 606.0, 285.0], [20.0, 30.0, 80.0, 90.0]]),
            scores=jnp.asarray([0.536, 0.71]),
            labels=jnp.asarray([0, 1]),
        )
    ]
    target = [
        dict(
            boxes=jnp.asarray([[214.0, 41.0, 562.0, 285.0], [25.0, 35.0, 85.0, 95.0]]),
            labels=jnp.asarray([0, 1]),
        )
    ]

    metric = MeanAveragePrecision(class_metrics=True)
    metric.update(preds, target)
    pprint({k: (v.tolist() if v.ndim else float(v)) for k, v in metric.compute().items()})
