"""Sharded evaluation with the pure-functional step API.

Runs a full evaluation epoch as ONE compiled XLA program over a data-
parallel mesh: inputs sharded over ``dp``, a ``lax.scan`` over batches on
each shard, and the metric states psum-reduced across the mesh inside the
same program (`make_step(..., axis_name="dp")`). This is the TPU-native
shape of the reference's DDP evaluation loop — no per-batch dispatches, no
eager all-gathers.

Works anywhere: provisions an 8-device virtual CPU mesh when no multi-chip
backend is initialized, exactly like the test suite.
"""
import sys
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo-root run without install

import jax

try:  # self-provision a virtual mesh when the backend allows it
    from jax._src import xla_bridge

    if not xla_bridge.backends_are_initialized():
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import AUROC, Accuracy, MeanSquaredError, make_step

N_DEV = min(8, jax.device_count())
N_BATCHES, BATCH, N_CLASSES = 10, 64 * N_DEV, 5
PER_DEV = BATCH // N_DEV

mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("dp",))

acc_init, acc_step, acc_compute = make_step(Accuracy, num_classes=N_CLASSES, axis_name="dp")
mse_init, mse_step, mse_compute = make_step(MeanSquaredError, axis_name="dp")
# sample-state metric: per-device CapacityBuffers fill locally; compute
# gathers data + fill counts across dp and runs the exact sort in-graph
auc_init, auc_step, auc_compute = make_step(
    AUROC, sample_capacity=N_BATCHES * PER_DEV, axis_name="dp", with_value=False
)


@jax.jit
@partial(jax.shard_map, mesh=mesh, in_specs=(P(None, "dp"), P(None, "dp")), out_specs=(P(), P(), P()))
def eval_epoch(preds, target):
    """(n_batches, BATCH/dp, C) shard -> globally reduced metric values."""

    def body(carry, batch):
        acc_state, mse_state, auc_state = carry
        p, t = batch
        acc_state, _ = acc_step(acc_state, p, t)
        mse_state, _ = mse_step(mse_state, p.max(axis=-1), t.astype(p.dtype) / N_CLASSES)
        auc_state, _ = auc_step(auc_state, p[:, 1], (t == 1).astype(jnp.int32))
        return (acc_state, mse_state, auc_state), None

    # the initial states are replicated constants while the scanned updates
    # are dp-varying; pcast once so the carry types line up (see the
    # shard_map varying-axes docs). The AUROC buffers must be ALLOCATED
    # before the scan fixes the carry structure: one unrolled step does it.
    (acc0, mse0, auc0) = (acc_init(), mse_init(), auc_init())
    p0, t0 = preds[0], target[0]
    acc0, _ = acc_step(acc0, p0, t0)
    mse0, _ = mse_step(mse0, p0.max(axis=-1), t0.astype(p0.dtype) / N_CLASSES)
    auc0, _ = auc_step(auc0, p0[:, 1], (t0 == 1).astype(jnp.int32))
    (acc_state, mse_state, auc_state), _ = jax.lax.scan(
        body, (acc0, mse0, auc0), (preds[1:], target[1:])
    )
    # the scan carry re-enters as tracers, erasing the buffers' trace-time
    # fill counts; the epoch length is static, so declare them back
    for buf in auc_state.values():
        buf.declare_count(N_BATCHES * PER_DEV)
    return acc_compute(acc_state), mse_compute(mse_state), auc_compute(auc_state)


def main() -> None:
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.random((N_BATCHES, BATCH, N_CLASSES)), jnp.float32)
    target = jnp.asarray(rng.integers(0, N_CLASSES, (N_BATCHES, BATCH)))

    accuracy, mse, auc = eval_epoch(preds, target)

    # parity with the eager class API on the unsharded data
    eager_acc = Accuracy(num_classes=N_CLASSES)
    eager_mse = MeanSquaredError()
    eager_auc = AUROC()
    for p, t in zip(preds, target):
        eager_acc.update(p, t)
        eager_mse.update(p.max(axis=-1), t.astype(p.dtype) / N_CLASSES)
    # the sharded AUROC consumed samples in device-major order; order does
    # not matter for the exact sort, so feed the eager oracle all data
    eager_auc.update(preds[:, :, 1].reshape(-1), (target.reshape(-1) == 1).astype(jnp.int32))
    np.testing.assert_allclose(float(accuracy), float(eager_acc.compute()), atol=1e-6)
    np.testing.assert_allclose(float(mse), float(eager_mse.compute()), atol=1e-6)
    np.testing.assert_allclose(float(auc), float(eager_auc.compute()), atol=1e-6)
    print(
        f"devices={N_DEV} accuracy={float(accuracy):.4f} mse={float(mse):.4f}"
        f" auroc={float(auc):.4f} (all match eager; AUROC's sample buffers"
        " gathered in-graph)"
    )


if __name__ == "__main__":
    main()
