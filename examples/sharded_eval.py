"""Sharded evaluation with the pure-functional step API.

Runs a full evaluation epoch as ONE compiled XLA program over a data-
parallel mesh: inputs sharded over ``dp``, a ``lax.scan`` over batches on
each shard, and the metric states psum-reduced across the mesh inside the
same program (`make_step(..., axis_name="dp")`). This is the TPU-native
shape of the reference's DDP evaluation loop — no per-batch dispatches, no
eager all-gathers.

Works anywhere: provisions an 8-device virtual CPU mesh when no multi-chip
backend is initialized, exactly like the test suite.
"""
import sys
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo-root run without install

import jax

try:  # self-provision a virtual mesh when the backend allows it
    from jax._src import xla_bridge

    if not xla_bridge.backends_are_initialized():
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import Accuracy, MeanSquaredError, make_step

N_DEV = min(8, jax.device_count())
N_BATCHES, BATCH, N_CLASSES = 10, 64 * N_DEV, 5

mesh = Mesh(np.array(jax.devices()[:N_DEV]), ("dp",))

acc_init, acc_step, acc_compute = make_step(Accuracy, num_classes=N_CLASSES, axis_name="dp")
mse_init, mse_step, mse_compute = make_step(MeanSquaredError, axis_name="dp")


@jax.jit
@partial(jax.shard_map, mesh=mesh, in_specs=(P(None, "dp"), P(None, "dp")), out_specs=(P(), P()))
def eval_epoch(preds, target):
    """(n_batches, BATCH/dp, C) shard -> globally reduced metric values."""

    def body(carry, batch):
        acc_state, mse_state = carry
        p, t = batch
        acc_state, _ = acc_step(acc_state, p, t)
        mse_state, _ = mse_step(mse_state, p.max(axis=-1), t.astype(p.dtype) / N_CLASSES)
        return (acc_state, mse_state), None

    # the initial states are replicated constants while the scanned updates
    # are dp-varying; pcast once so the carry types line up (see the
    # shard_map varying-axes docs)
    init_carry = jax.lax.pcast((acc_init(), mse_init()), ("dp",), to="varying")
    (acc_state, mse_state), _ = jax.lax.scan(body, init_carry, (preds, target))
    return acc_compute(acc_state), mse_compute(mse_state)


def main() -> None:
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.random((N_BATCHES, BATCH, N_CLASSES)), jnp.float32)
    target = jnp.asarray(rng.integers(0, N_CLASSES, (N_BATCHES, BATCH)))

    accuracy, mse = eval_epoch(preds, target)

    # parity with the eager class API on the unsharded data
    eager_acc = Accuracy(num_classes=N_CLASSES)
    eager_mse = MeanSquaredError()
    for p, t in zip(preds, target):
        eager_acc.update(p, t)
        eager_mse.update(p.max(axis=-1), t.astype(p.dtype) / N_CLASSES)
    np.testing.assert_allclose(float(accuracy), float(eager_acc.compute()), atol=1e-6)
    np.testing.assert_allclose(float(mse), float(eager_mse.compute()), atol=1e-6)
    print(f"devices={N_DEV} accuracy={float(accuracy):.4f} mse={float(mse):.4f} (both match eager)")


if __name__ == "__main__":
    main()
