"""Always-on model monitoring: windowed streaming AUROC + drift alerts.

A serving fleet cannot run epochs: requests arrive forever, memory must
stay flat, and "the metric" means "the metric over the last window of
traffic". This example simulates such a stream — a binary scorer whose
input distribution silently degrades halfway through — and monitors it
with the streaming subsystem:

* ``WindowedMetric(StreamingAUROC(...))`` driven by ``make_stream_step``:
  each batch is ONE compiled launch that folds the sketch, rotates/expires
  the window ring in-graph, and emits the current window AUROC with its
  documented error bound.
* a ``DriftMonitor`` frozen on the validation-time score distribution,
  alerting through ``metrics_tpu.obs`` counters when PSI crosses 0.2.
* a mid-stream ``ft.CheckpointManager`` save + simulated preemption: the
  resumed monitor reproduces the window value bitwise.

Run: ``python examples/streaming_monitor.py``
"""
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo-root run without install

import jax
import jax.numpy as jnp
import numpy as np

import metrics_tpu.obs as obs
from metrics_tpu.ft import BatchJournal, CheckpointManager
from metrics_tpu.steps import make_stream_step
from metrics_tpu.streaming import DriftMonitor, StreamingAUROC, WindowedMetric

BATCH = 4_096
N_BATCHES = 24
DRIFT_AT = 12  # the input distribution degrades from this batch on
WINDOW, UPDATES_PER_SLOT = 4, 2  # window = last 8 batches


def serve_batch(rng: np.random.Generator, step: int):
    """One batch of (score, label) pairs; after DRIFT_AT the feature
    pipeline 'breaks' — scores compress toward 0 and lose their signal."""
    scores = rng.uniform(0, 1, BATCH).astype(np.float32)
    labels = (rng.uniform(0, 1, BATCH) < 0.2 + 0.6 * scores).astype(np.int32)
    if step >= DRIFT_AT:
        scores = (scores * 0.35).astype(np.float32)  # compressed + miscalibrated
    return jnp.asarray(scores), jnp.asarray(labels)


def main() -> None:
    obs.enable()
    rng = np.random.default_rng(0)

    # frozen validation-time reference for the drift monitor: coarse bins
    # (64 over 4k samples/batch) so PSI measures distribution shift, not
    # per-bin sampling noise
    val_scores, val_labels = serve_batch(rng, step=0)
    reference = StreamingAUROC(num_bins=64)
    reference.update(val_scores, val_labels)
    monitor = DriftMonitor(reference, psi_threshold=0.2, name="prod-scores", warn=False)

    windowed = WindowedMetric(
        StreamingAUROC(num_bins=512), window=WINDOW, updates_per_slot=UPDATES_PER_SLOT
    )
    init, stream_step, compute = make_stream_step(windowed)
    state = init()

    ckpt_dir = tempfile.mkdtemp(prefix="stream_monitor.")
    manager = CheckpointManager(ckpt_dir, keep_last=2)
    journal = BatchJournal()
    live = StreamingAUROC(num_bins=64)  # eager twin: feeds the drift check

    print(f"{'batch':>5} {'window AUROC':>13} {'±bound':>8} {'PSI':>7}  alert")
    saved_at = None
    for step_i in range(N_BATCHES):
        scores, labels = serve_batch(rng, step_i)
        state, window_auroc = stream_step(state, scores, labels)  # ONE launch
        live.update(scores, labels)
        report = monitor.check(live)
        # the error bound is itself computable from the carried sketch
        bound_metric = StreamingAUROC(num_bins=512)
        bound_metric.sketch = state["slots"]["sketch"].reduce_leading_axis()
        bound_metric._update_count = 1
        journal.record(0, step_i)
        if step_i == N_BATCHES // 2:  # preemption-safe save mid-stream
            snapshot = jax.tree_util.tree_map(jnp.array, state)  # pre-donation copy
            manager.save(bound_metric, journal=journal, epoch=0, step=step_i)
            saved_at = (snapshot, float(window_auroc))
        flag = "  <-- DRIFT" if report["alert"] else ""
        print(
            f"{step_i:>5} {float(window_auroc):>13.4f}"
            f" {float(bound_metric.error_bound()):>8.5f} {report['psi']:>7.3f}{flag}"
        )

    assert obs.get_counter("stream.drift_alerts", monitor="prod-scores") > 0
    assert obs.get_counter("stream.windows_expired", metric="StreamingAUROC") > 0

    # simulated preemption: resume from the saved carry, same window value
    snapshot, value_then = saved_at
    resumed_value = float(compute(snapshot))
    print(f"\nresumed window AUROC from checkpointed carry: {resumed_value:.6f}"
          f" (at save time: {value_then:.6f})")
    assert resumed_value == value_then
    restored = StreamingAUROC(num_bins=512)
    j2 = BatchJournal()
    manifest = manager.restore(restored, journal=j2)
    print(f"manifest watermark {manifest['journal']['watermark']};"
          f" next batch to fold: {tuple(j2.resume_from)}")
    print(f"sketch state on device: {restored.sketch.nbytes} bytes for"
          f" {int(float(restored.sketch.count))} folded samples")


if __name__ == "__main__":
    main()
