"""Evaluate a torch data pipeline with metrics_tpu — no conversion code.

The migration story in one script (docs/migration.md): an existing torch
``DataLoader`` eval loop, exactly as a user of the reference wrote it,
drives a ``MetricCollection`` unchanged — ``update``/``forward`` accept
``torch.Tensor`` batches (nested dicts included) and convert them on entry,
while the metric state itself lives as jax arrays on the accelerator.

Run: ``python examples/torch_pipeline_eval.py``
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo-root run without install

from pprint import pprint

import numpy as np
import torch
from torch.utils.data import DataLoader, TensorDataset

from metrics_tpu import Accuracy, F1Score, MetricCollection
from metrics_tpu.detection import MeanAveragePrecision

N, N_CLASSES, BATCH = 2_048, 5, 256

# ---- a torch pipeline, verbatim from a reference user's codebase ---------
g = torch.Generator().manual_seed(0)
logits = torch.randn(N, N_CLASSES, generator=g)
target = torch.where(
    torch.rand(N, generator=g) < 0.75, logits.argmax(1), torch.randint(0, N_CLASSES, (N,), generator=g)
)
loader = DataLoader(TensorDataset(logits.softmax(1), target), batch_size=BATCH)

metrics = MetricCollection(
    {
        "acc": Accuracy(num_classes=N_CLASSES),
        "macro_f1": F1Score(num_classes=N_CLASSES, average="macro"),
    }
)

for preds_b, target_b in loader:  # torch tensors straight in
    batch_vals = metrics(preds_b, target_b)
print("last-batch values:", {k: round(float(v), 4) for k, v in batch_vals.items()})
pprint({k: round(float(v), 4) for k, v in metrics.compute().items()})

# ---- nested inputs: detection dicts stay torch too -----------------------
boxes = torch.tensor([[12.0, 10.0, 80.0, 75.0], [100.0, 100.0, 160.0, 150.0]])
map_metric = MeanAveragePrecision()
map_metric.update(
    [dict(boxes=boxes, scores=torch.tensor([0.9, 0.6]), labels=torch.tensor([1, 3]))],
    [dict(boxes=boxes, labels=torch.tensor([1, 3]))],
)
print("detection map (torch dict inputs):", round(float(map_metric.compute()["map"]), 4))

acc_np = float(
    (np.asarray(logits.softmax(1)).argmax(1) == np.asarray(target)).mean()
)
assert abs(float(metrics.compute()["acc"]) - acc_np) < 1e-6
print("matches the numpy cross-check: OK")
