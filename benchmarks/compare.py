"""Round-over-round bench comparison: noise-aware deltas, regression gate.

Five ``BENCH_rNN.json`` records accumulated before this module existed with
zero tooling to diff them — a hot path could get 1.5x slower between
rounds and nothing would say so. This module is that gate:

* :func:`load_record` reads BOTH record shapes in the tree — the driver's
  ``{"tail": <stdout tail>}`` captures (rows parsed back out of the JSON
  lines, keeping the best value per metric) and ``bench.py --json``'s
  self-describing ``{"rows": [...], "device_kind": ...}`` records.
* :func:`compare_records` computes per-row deltas between two records and
  gates them. The published ``value`` of a row is already the fast-mode
  median of the bimodal-chip protocol (``benchmarks/_timing.py``); the
  comparison is **noise-aware** on top of that: a side whose
  ``n_fast`` sample count is below ``min_n_fast`` (or whose slow-mode
  samples outnumber its fast ones) is marked low-confidence and never
  gates, and when both records carry the chip-state probe rows the gate
  compares the **row/probe ratio** instead of raw values — the per-op-class
  chip state cancels out of the ratio, so a slow chip session cannot fake
  a regression (the same protocol ``bench.py`` applies against its best
  prior round). Probe rows themselves record state and are never gated.
* **Cross-device refusal**: records carry ``device_kind``; comparing a TPU
  sweep against a CPU fallback is meaningless and exits with its own code
  (:data:`EXIT_REFUSED`) and a clear message rather than a wall of fake
  regressions. Driver-tail records predate the header and compare with a
  confound warning.
* :func:`trend_table` renders the metric x round markdown table across
  ``BENCH_r01..rNN``.

CLI (also wired as ``bench.py --compare OLD.json``)::

    python -m benchmarks.compare OLD.json NEW.json [--threshold 1.5]
    python -m benchmarks.compare --trend BENCH_r*.json

Exit codes: 0 pass, :data:`EXIT_REGRESSED` (1) at least one gated row
regressed past the threshold, :data:`EXIT_REFUSED` (2) cross-device or
unreadable input.
"""
import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "EXIT_OK",
    "EXIT_REFUSED",
    "EXIT_REGRESSED",
    "PROBE_CLASS",
    "BenchRecord",
    "CompareRefused",
    "compare_records",
    "load_record",
    "render_report",
    "rows_by_metric",
    "trend_table",
]

EXIT_OK = 0
EXIT_REGRESSED = 1
EXIT_REFUSED = 2

# which chip-state probe calibrates which row, by the row's dominant op
# class (bench.py emits the probe rows; see bench_probes there). Shared
# with bench.py's against-best-prior gate so the two gates can never
# disagree about a row's calibration class.
PROBE_CLASS: Dict[str, str] = {
    "auroc_exact_1M_compute": "probe_sort_1M",
    "retrieval_map_1M_docs_compute": "probe_sort_1M",
    "retrieval_ndcg_1M_docs_compute": "probe_sort_1M",
    "retrieval_map_k10_1M_docs_compute": "probe_sort_1M",
    "fid_10k_2048d_compute": "probe_matmul_1024_bf16",
    "bertscore_match_256x128x256": "probe_matmul_1024_bf16",
    "lpips_alex_32x64x64_forward": "probe_conv_64ch_3x3",
    "ssim_64x3x256x256_compute": "probe_elementwise_1Mx10",
    "accuracy_1M_update_compute_wallclock": "probe_elementwise_1Mx10",
    "binned_counts_1M_T100_update": "probe_elementwise_1Mx10",
    "collection_statscores_binary_1M_update": "probe_elementwise_1Mx10",
    "collection_statscores_multiclass_1M_update": "probe_elementwise_1Mx10",
    # fused whole-collection epoch: compare/one-hot/reduce dominated
    # (collection12_launch_count is a COUNT row — no probe; its raw ratio
    # pins fusion at one launch per epoch)
    "collection12_1M_epoch_wallclock": "probe_elementwise_1Mx10",
    # serving tier (loadgen through a 3-level tree): the jitted stacked
    # fold is elementwise/reduce dominated; the host-side decode/dedup
    # share moves with the same chip state only loosely, so these rows
    # also carry process_count and the rate row gates INVERTED (see
    # is_rate_metric)
    "serve_ingest_merges_per_s": "probe_elementwise_1Mx10",
    "serve_ingest_p99_ms": "probe_elementwise_1Mx10",
}


def is_rate_metric(name: str, *rows: Any) -> bool:
    """True for higher-is-better rows — throughput (``unit="/s"`` /
    ``*_per_s``) and percentage-recovered rows (``unit="%"`` / ``*_pct``,
    e.g. the prefetch-overlap row): the regression gate, the best-prior
    scan and the duplicate keep-best rule all invert for them."""
    if isinstance(name, str) and (name.endswith("_per_s") or name.endswith("_pct")):
        return True
    return any(isinstance(r, dict) and r.get("unit") in ("/s", "%") for r in rows)


class CompareRefused(RuntimeError):
    """Raised when two records are not comparable (cross-device, unreadable)."""


def rows_by_metric(rows: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Normalize a row list to ``{metric: row}``, keeping the best (lowest)
    value per duplicate metric and dropping malformed rows — the ONE
    normalization every record path shares, so an in-memory record can
    never gate differently from the same record reloaded from disk."""
    out: Dict[str, Dict[str, Any]] = {}
    for row in rows:
        name, value = row.get("metric"), row.get("value")
        if not isinstance(name, str) or not isinstance(value, (int, float)) or value <= 0:
            continue
        prev = out.get(name)
        if prev is None or (
            value > prev["value"] if is_rate_metric(name, row) else value < prev["value"]
        ):
            out[name] = row
    return out


class BenchRecord:
    """One normalized bench record: ``{metric: row}`` plus the header."""

    def __init__(
        self,
        rows: Dict[str, Dict[str, Any]],
        path: str = "<memory>",
        device_kind: Optional[str] = None,
        platform: Optional[str] = None,
        jax_version: Optional[str] = None,
        device_count: Optional[int] = None,
        process_count: Optional[int] = None,
        source: str = "record",
    ) -> None:
        self.rows = rows
        self.path = path
        self.device_kind = device_kind
        self.platform = platform
        self.jax_version = jax_version
        self.device_count = device_count
        self.process_count = process_count
        self.source = source

    def header(self) -> str:
        """One human-readable line: where the record ran."""
        dev = self.device_kind or "unknown-device"
        parts = [f"device_kind={dev}"]
        if self.platform:
            parts.append(f"platform={self.platform}")
        if self.device_count is not None:
            parts.append(f"devices={self.device_count}")
        if self.process_count is not None:
            parts.append(f"hosts={self.process_count}")
        parts.append(f"jax={self.jax_version or 'unknown'}")
        return f"{os.path.basename(self.path)}: {', '.join(parts)} [{self.source}]"

    def __repr__(self) -> str:
        return f"BenchRecord({self.header()}, {len(self.rows)} rows)"


def _rows_from_lines(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse bench stdout JSON lines (duplicate lines from the repeated
    final table are harmless — :func:`rows_by_metric` keeps the best)."""
    rows: List[Dict[str, Any]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rows.append(json.loads(line))
        except ValueError:
            continue
    return rows_by_metric(rows)


def load_record(path: str) -> BenchRecord:
    """Read a bench record off disk, whichever of the two shapes it is."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError) as err:
        raise CompareRefused(f"cannot read bench record {path!r}: {err}") from err
    if isinstance(data, dict) and isinstance(data.get("rows"), list):
        return BenchRecord(
            rows_by_metric(data["rows"]),
            path=path,
            device_kind=data.get("device_kind"),
            platform=data.get("platform"),
            jax_version=data.get("jax_version"),
            device_count=data.get("device_count"),
            process_count=data.get("process_count"),
            source="record",
        )
    if isinstance(data, dict) and isinstance(data.get("tail"), str):
        return BenchRecord(_rows_from_lines(data["tail"]), path=path, source="driver_tail")
    raise CompareRefused(
        f"unrecognized bench record shape in {path!r}: expected a bench.py --json"
        " record (\"rows\" list) or a driver capture (\"tail\" string)"
    )


def _row_value(row: Dict[str, Any]) -> float:
    """The comparable number for a row: the fast-mode median when the
    bimodal protocol recorded one, else the published value."""
    fast = row.get("fast_mode_median")
    if isinstance(fast, (int, float)) and fast > 0:
        return float(fast)
    return float(row["value"])


def _row_confidence(row: Dict[str, Any], min_n_fast: int) -> Optional[str]:
    """``None`` when the row's measurement is gate-grade, else the reason
    it is low-confidence (few fast-mode samples, slow-mode dominated)."""
    n_fast = row.get("n_fast")
    if n_fast is None:
        return None  # pre-protocol row: no sample counts to judge by
    n_slow = row.get("n_slow") or 0
    if n_fast < min_n_fast:
        return f"n_fast={n_fast}<{min_n_fast}"
    if n_slow > n_fast:
        return f"slow-mode dominated ({n_slow}>{n_fast})"
    return None


def compare_records(
    old: BenchRecord,
    new: BenchRecord,
    threshold: float = 1.5,
    min_n_fast: int = 2,
    allow_cross_device: bool = False,
) -> Dict[str, Any]:
    """Diff two records row by row; gate regressions past ``threshold``.

    Returns ``{"rows": [...], "regressions": [names], "exit_code": int,
    "old", "new"}``. Each output row carries ``metric``, ``old_ms``,
    ``new_ms``, ``ratio`` (new/old), ``norm_ratio`` (row/probe-normalized,
    when both sides carry the row's chip-state probe), ``verdict`` in
    ``{"ok", "REGRESSION", "improved", "low-confidence", "probe", "new",
    "removed"}`` and a ``note``. The gate uses ``norm_ratio`` when
    available (chip-state invariant), the raw ``ratio`` otherwise.
    """
    if (
        not allow_cross_device
        and old.device_kind is not None
        and new.device_kind is not None
        and old.device_kind != new.device_kind
    ):
        raise CompareRefused(
            f"refusing to compare across device kinds: {old.path} ran on"
            f" {old.device_kind!r} but {new.path} ran on {new.device_kind!r}."
            " A latency delta between different hardware measures the hardware,"
            " not the code — rerun the old sweep on the new device kind, or pass"
            " --allow-cross-device to override."
        )
    out_rows: List[Dict[str, Any]] = []
    regressions: List[str] = []
    for name in sorted(set(old.rows) | set(new.rows)):
        o, n = old.rows.get(name), new.rows.get(name)
        if o is None or n is None:
            out_rows.append(
                {
                    "metric": name,
                    "old_ms": None if o is None else _row_value(o),
                    "new_ms": None if n is None else _row_value(n),
                    "ratio": None,
                    "norm_ratio": None,
                    "verdict": "new" if o is None else "removed",
                    "note": "",
                }
            )
            continue
        old_v, new_v = _row_value(o), _row_value(n)
        # rate rows (throughput, higher better) gate on the INVERSE ratio
        # so ">threshold = regression" reads the same for every row; their
        # probe normalization multiplies instead of divides (throughput
        # and probe latency scale inversely with the same chip state)
        rate = is_rate_metric(name, o, n)
        ratio = (old_v / new_v) if rate else (new_v / old_v)
        probe = PROBE_CLASS.get(name)
        norm_ratio = None
        if probe and probe in old.rows and probe in new.rows:
            old_p, new_p = _row_value(old.rows[probe]), _row_value(new.rows[probe])
            if old_p > 0 and new_p > 0:
                norm_ratio = (
                    (old_v * old_p) / (new_v * new_p) if rate else (new_v / new_p) / (old_v / old_p)
                )
        note_parts = []
        if rate:
            note_parts.append("rate row (higher is better): Δ× is old/new")
        conf = _row_confidence(o, min_n_fast) or _row_confidence(n, min_n_fast)
        effective = norm_ratio if norm_ratio is not None else ratio
        if name.startswith("probe_"):
            verdict = "probe"  # probes RECORD chip state; gating them is meaningless
        elif conf is not None:
            verdict = "low-confidence"
            note_parts.append(conf)
        elif effective > threshold:
            verdict = "REGRESSION"
            regressions.append(name)
            if norm_ratio is None and probe:
                note_parts.append("no probe on one side: raw (chip-state-confounded) ratio")
        elif effective < 1.0 / threshold:
            verdict = "improved"
        else:
            verdict = "ok"
        if norm_ratio is not None:
            note_parts.append("probe-normalized gate")
        out_rows.append(
            {
                "metric": name,
                "old_ms": old_v,
                "new_ms": new_v,
                "ratio": ratio,
                "norm_ratio": norm_ratio,
                "verdict": verdict,
                "note": "; ".join(note_parts),
            }
        )
    return {
        "rows": out_rows,
        "regressions": regressions,
        "exit_code": EXIT_REGRESSED if regressions else EXIT_OK,
        "old": old,
        "new": new,
        "threshold": threshold,
    }


def _fmt(v: Optional[float], pattern: str = "{:.3f}") -> str:
    return "—" if v is None else pattern.format(v)


def render_report(result: Dict[str, Any]) -> str:
    """Markdown report: header lines (device/jax/hosts of both records),
    the per-row delta table, and the gate verdict."""
    old, new = result["old"], result["new"]
    lines = [
        "# Bench comparison",
        "",
        f"- old: {old.header()}",
        f"- new: {new.header()}",
        f"- gate threshold: {result['threshold']}x"
        + " (row/probe-normalized where probes exist on both sides)",
    ]
    if old.device_kind is None or new.device_kind is None:
        lines.append(
            "- WARNING: at least one record carries no device_kind (driver-tail"
            " capture) — deltas may be confounded by hardware differences."
        )
    lines += [
        "",
        "| metric | old ms | new ms | Δ× | norm Δ× | verdict | note |",
        "|---|---|---|---|---|---|---|",
    ]
    for row in result["rows"]:
        lines.append(
            f"| {row['metric']} | {_fmt(row['old_ms'])} | {_fmt(row['new_ms'])} |"
            f" {_fmt(row['ratio'], '{:.2f}')} | {_fmt(row['norm_ratio'], '{:.2f}')} |"
            f" {row['verdict']} | {row['note']} |"
        )
    lines.append("")
    if result["regressions"]:
        lines.append(
            f"**GATE: FAIL — {len(result['regressions'])} regression(s):"
            f" {', '.join(result['regressions'])}**"
        )
    else:
        lines.append("GATE: pass")
    return "\n".join(lines) + "\n"


def trend_table(paths: List[str]) -> str:
    """Markdown metric x round trend table across bench records, in the
    given order (pass ``BENCH_r*.json`` sorted for the chronology)."""
    records = [load_record(p) for p in paths]
    names = sorted({name for rec in records for name in rec.rows})
    heads = [os.path.basename(p).replace(".json", "") for p in paths]
    lines = [
        "# Bench trend (ms; fast-mode median where recorded)",
        "",
        "| metric | " + " | ".join(heads) + " |",
        "|---|" + "---|" * len(heads),
    ]
    for name in names:
        cells = []
        for rec in records:
            row = rec.rows.get(name)
            cells.append("—" if row is None else f"{_row_value(row):.3f}")
        lines.append(f"| {name} | " + " | ".join(cells) + " |")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("records", nargs="*", help="OLD.json NEW.json (or record list with --trend)")
    parser.add_argument("--threshold", type=float, default=1.5, help="gate at new/old > this (default 1.5)")
    parser.add_argument(
        "--min-n-fast", type=int, default=2,
        help="rows with fewer fast-mode samples on either side are low-confidence and never gate",
    )
    parser.add_argument(
        "--allow-cross-device", action="store_true",
        help="compare records from different device kinds anyway (deltas measure the hardware!)",
    )
    parser.add_argument(
        "--trend", action="store_true",
        help="render the metric x round trend table over the given records instead of gating",
    )
    parser.add_argument("--markdown", metavar="PATH", default=None, help="also write the report to PATH")
    args = parser.parse_args(argv)

    try:
        if args.trend:
            paths: List[str] = []
            for pattern in args.records or [os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "BENCH_r*.json")]:
                expanded = sorted(glob.glob(pattern))
                paths.extend(expanded if expanded else [pattern])
            if not paths:
                raise CompareRefused("--trend found no bench records")
            report = trend_table(paths)
            code = EXIT_OK
        else:
            if len(args.records) != 2:
                parser.error("compare mode needs exactly two records: OLD.json NEW.json")
            old, new = load_record(args.records[0]), load_record(args.records[1])
            result = compare_records(
                old, new,
                threshold=args.threshold,
                min_n_fast=args.min_n_fast,
                allow_cross_device=args.allow_cross_device,
            )
            report = render_report(result)
            code = result["exit_code"]
    except CompareRefused as err:
        print(f"REFUSED: {err}", file=sys.stderr)
        return EXIT_REFUSED
    print(report, end="")
    if args.markdown:
        with open(args.markdown, "w") as f:
            f.write(report)
    return code


if __name__ == "__main__":
    raise SystemExit(main())
