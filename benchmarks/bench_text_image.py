"""LPIPS forward and BERTScore greedy-matching benches (BASELINE.md configs).

LPIPS: the in-repo Flax AlexNet tower + heads, one jitted two-tower
program on (32, 3, 64, 64) image pairs. BERTScore: the device-side scoring
kernel (`_bert_score_kernel`: normalize -> mask -> (B, S, S) cosine matrix
-> greedy match -> P/R/F1) on (256, 128, 256) padded embeddings — the part
of the metric the reference runs as eager torch ops
(``functional/text/bert.py:327-360``); the encoder forward is model-bound
and benched separately by its owner.
"""
import functools
import json

import jax
import jax.numpy as jnp

from benchmarks._timing import measure_ms_scaled

LPIPS_SHAPE = (32, 3, 64, 64)
BS_B, BS_S, BS_D = 256, 128, 256
K_LPIPS = 100  # ~3 ms/forward: K must swamp even second-scale RTT spikes
K_BS = 200


def measure_lpips() -> float:
    from metrics_tpu.image.backbones import NoTrainLpips

    net = NoTrainLpips("alex", rng_seed=0, allow_random_weights=True)
    a = jax.random.uniform(jax.random.PRNGKey(0), LPIPS_SHAPE, minval=-1, maxval=1)
    b = jax.random.uniform(jax.random.PRNGKey(1), LPIPS_SHAPE, minval=-1, maxval=1)

    from metrics_tpu.image.backbones.lpips_nets import _lpips_forward

    def make_run(k):
        @jax.jit
        def run(a=a, b=b):
            def body(i, acc):
                # scale BOTH inputs so neither tower is loop-invariant
                # (XLA would hoist a constant tower out of the loop)
                scale = 1.0 - 0.0001 * i.astype(jnp.float32)
                return acc + _lpips_forward(net.module, net.variables, a * scale, b * scale).sum()

            return jax.lax.fori_loop(0, k, body, jnp.zeros(()))
        return run

    # K auto-doubles until the workload swamps tunnel RTT phase noise (the
    # r02 run SKIPPED this row at fixed K=100)
    return measure_ms_scaled(make_run, K_LPIPS)


def measure_bertscore() -> float:
    from metrics_tpu.functional.text.bert import _bert_score_kernel

    emb_p = jax.random.normal(jax.random.PRNGKey(0), (BS_B, BS_S, BS_D))
    emb_t = jax.random.normal(jax.random.PRNGKey(1), (BS_B, BS_S, BS_D))
    mask = jnp.ones((BS_B, BS_S), jnp.float32)
    idf_w = jnp.ones((BS_B, BS_S), jnp.float32)

    def make_run(k):
        @jax.jit
        def run(emb_p=emb_p, emb_t=emb_t):
            def body(i, acc):
                p, r, f1 = _bert_score_kernel(
                    emb_p * (1.0 + 0.0001 * i), mask, idf_w, emb_t, mask, idf_w, idf=True
                )
                return acc + f1.sum()

            return jax.lax.fori_loop(0, k, body, jnp.zeros(()))
        return run

    return measure_ms_scaled(make_run, K_BS)


@functools.lru_cache(maxsize=2)
def wer_corpus(n_pairs: int = 10_000, n_words: int = 20, vocab: int = 500, seed: int = 0):
    """Synthetic ASR-style corpus: target sentences plus predictions with
    ~15% word substitutions and occasional deletions (cached — bench.py's
    baseline re-times the same corpus)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    words = [f"w{i}" for i in range(vocab)]
    preds, targets = [], []
    for _ in range(n_pairs):
        n = int(rng.integers(max(2, n_words // 2), n_words * 2))
        tgt = [words[i] for i in rng.integers(0, vocab, n)]
        pred = [w if rng.uniform() > 0.15 else words[int(rng.integers(0, vocab))] for w in tgt]
        if rng.uniform() < 0.3 and len(pred) > 2:
            del pred[int(rng.integers(0, len(pred)))]
        targets.append(" ".join(tgt))
        preds.append(" ".join(pred))
    return preds, targets


def measure_wer(n_pairs: int = 10_000) -> float:
    """Corpus WER through the shipped host path (tokenize, intern to int64
    ids, ONE batched native-C Levenshtein crossing — numpy fallback when no
    compiler). The reference runs a per-pair pure-python DP loop
    (reference ``functional/text/wer.py:23-48``).

    SPLIT reporting: the published value is the HOST KERNEL time (the part
    this repo implements); the end-to-end ``word_error_rate`` call adds one
    tunnel round trip for the device scalar, whose 20us-90ms phase swing
    dominated the old combined number (~80% of the 133 ms round-5 row was
    RTT). The measured round-trip share rides along as ``tunnel_rtt_ms`` —
    compare it with the sweep's ``probe_tunnel_rtt`` row.
    """
    import time

    from benchmarks._timing import cluster_direct_samples
    from metrics_tpu.functional import word_error_rate
    from metrics_tpu.functional.text.helper import _corpus_edit_stats, _normalize_corpus

    preds, targets = wer_corpus(n_pairs)
    word_error_rate(preds, targets)  # warm (compiles the .so on first use)
    host_times, full_times = [], []
    for _ in range(8):
        t0 = time.perf_counter()
        p, t = _normalize_corpus(preds, targets)
        dists, _, cnt_t = _corpus_edit_stats(p, t, "words")  # numpy: pure host
        _ = float(dists.sum()) / float(cnt_t.sum())
        host_times.append((time.perf_counter() - t0) * 1000)
        t1 = time.perf_counter()
        float(word_error_rate(preds, targets))  # float(): sync the device scalar
        full_times.append((time.perf_counter() - t1) * 1000)
    # direct wall-clock samples under the swinging RTT phase: cluster, don't
    # min-select (benchmarks/_timing.py)
    host = cluster_direct_samples(host_times)
    full = cluster_direct_samples(full_times)
    host.tunnel_rtt_ms = max(0.0, float(full) - float(host))
    return host


def measure() -> dict:
    return {
        "lpips_alex_32x64x64_forward": measure_lpips(),
        "bertscore_match_256x128x256": measure_bertscore(),
        "wer_10k_pairs_compute": measure_wer(),
    }


def main() -> None:
    for name, ms in measure().items():
        print(json.dumps({"metric": name, "value": round(ms, 3), "unit": "ms"}))


if __name__ == "__main__":
    main()
