"""LPIPS forward and BERTScore greedy-matching benches (BASELINE.md configs).

LPIPS: the in-repo Flax AlexNet tower + heads, one jitted two-tower
program on (32, 3, 64, 64) image pairs. BERTScore: the device-side scoring
kernel (`_bert_score_kernel`: normalize -> mask -> (B, S, S) cosine matrix
-> greedy match -> P/R/F1) on (256, 128, 256) padded embeddings — the part
of the metric the reference runs as eager torch ops
(``functional/text/bert.py:327-360``); the encoder forward is model-bound
and benched separately by its owner.
"""
import json

import jax
import jax.numpy as jnp

from benchmarks._timing import measure_ms_scaled

LPIPS_SHAPE = (32, 3, 64, 64)
BS_B, BS_S, BS_D = 256, 128, 256
K_LPIPS = 100  # ~3 ms/forward: K must swamp even second-scale RTT spikes
K_BS = 200


def measure_lpips() -> float:
    from metrics_tpu.image.backbones import NoTrainLpips

    net = NoTrainLpips("alex", rng_seed=0, allow_random_weights=True)
    a = jax.random.uniform(jax.random.PRNGKey(0), LPIPS_SHAPE, minval=-1, maxval=1)
    b = jax.random.uniform(jax.random.PRNGKey(1), LPIPS_SHAPE, minval=-1, maxval=1)

    from metrics_tpu.image.backbones.lpips_nets import _lpips_forward

    def make_run(k):
        @jax.jit
        def run(a=a, b=b):
            def body(i, acc):
                # scale BOTH inputs so neither tower is loop-invariant
                # (XLA would hoist a constant tower out of the loop)
                scale = 1.0 - 0.0001 * i.astype(jnp.float32)
                return acc + _lpips_forward(net.module, net.variables, a * scale, b * scale).sum()

            return jax.lax.fori_loop(0, k, body, jnp.zeros(()))
        return run

    # K auto-doubles until the workload swamps tunnel RTT phase noise (the
    # r02 run SKIPPED this row at fixed K=100)
    return measure_ms_scaled(make_run, K_LPIPS)


def measure_bertscore() -> float:
    from metrics_tpu.functional.text.bert import _bert_score_kernel

    emb_p = jax.random.normal(jax.random.PRNGKey(0), (BS_B, BS_S, BS_D))
    emb_t = jax.random.normal(jax.random.PRNGKey(1), (BS_B, BS_S, BS_D))
    mask = jnp.ones((BS_B, BS_S), jnp.float32)
    idf_w = jnp.ones((BS_B, BS_S), jnp.float32)

    def make_run(k):
        @jax.jit
        def run(emb_p=emb_p, emb_t=emb_t):
            def body(i, acc):
                p, r, f1 = _bert_score_kernel(
                    emb_p * (1.0 + 0.0001 * i), mask, idf_w, emb_t, mask, idf_w, idf=True
                )
                return acc + f1.sum()

            return jax.lax.fori_loop(0, k, body, jnp.zeros(()))
        return run

    return measure_ms_scaled(make_run, K_BS)


def measure() -> dict:
    return {
        "lpips_alex_32x64x64_forward": measure_lpips(),
        "bertscore_match_256x128x256": measure_bertscore(),
    }


def main() -> None:
    for name, ms in measure().items():
        print(json.dumps({"metric": name, "value": round(ms, 3), "unit": "ms"}))


if __name__ == "__main__":
    main()
