"""Mesh-native sharded-state + topology-aware sync benchmarks (round 15).

Three rows for the ``bench.py --json`` sweep:

* ``sharded_auroc_1M_sync_ms`` — a 1M-sample ``CapacityBuffer``-backed
  AUROC's sync+compute on the mesh: the SHARDED path (mesh-resident rows,
  ``lax.ppermute`` ring pair count — no materialized gather) timed against
  the replicated path (in-graph buffer all-gather + exact sort) as its
  baseline. Same folded states, same value.
* ``hier_reduce_vs_flat_ratio`` — the ICI-first/DCN-second per-axis psum
  chain over a 4 MB state on a 2 x (n/2) mesh, as a RATIO to the flat
  single-collective psum (unit ``x``, lower is better; < 1 means the
  topology-ordered chain wins).
* ``epoch_prefetch_overlap_pct`` — how much of a host-resident epoch's
  wall clock ``make_epoch(prefetch=K)`` recovers by overlapping the next
  chunk's ``jax.device_put`` with the in-flight fold, vs the same chunked
  fold with transfers serialized (unit ``%``, HIGHER is better — the gate
  inverts like a rate row).

``measure()`` needs >= 2 devices: ``bench.py`` calls it in-process on
multi-device hosts (the TPU sweep, which supplies acceptance values) and
as a subprocess on single-device CPU hosts, where ``__main__`` here
self-provisions an 8-device virtual CPU mesh BEFORE backend init —
emulated-device milliseconds are not ICI numbers, but the sharded/
replicated and overlap ratios are meaningful.
``measure_prefetch()`` is single-device and always runs in-process.
"""
import json
import time

N_SAMPLES = 1_000_000
N_BATCHES = 16


def _best_ms(fn, trials: int = 5) -> float:
    import jax

    fn()  # warm: trace + compile outside the timing
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, (time.perf_counter() - t0) * 1000.0)
    return best


def _shard_map(f, mesh, in_specs, out_specs):
    import jax
    import metrics_tpu  # noqa: F401  — compat shims install jax.shard_map

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def measure() -> dict:
    """The two mesh rows (needs >= 2 devices; see module docstring)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu import AUROC, make_step

    if jax.device_count() < 2:
        raise RuntimeError("bench_mesh.measure needs >= 2 devices (run __main__ to self-provision)")
    # largest power of two <= device_count, capped at 8: keeps the mesh
    # rectangular for the 2 x (n/2) hierarchical arm and divides the state
    n_dev = 1
    while n_dev * 2 <= min(8, jax.device_count()):
        n_dev *= 2
    mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("dp",))
    rng = np.random.default_rng(0)
    out: dict = {}

    # --- sharded vs replicated 1M buffer AUROC sync+compute ------------
    cap = N_SAMPLES // n_dev
    preds = jnp.asarray(rng.random(n_dev * cap, dtype=np.float32))
    target = jnp.asarray((rng.random(n_dev * cap) < 0.5).astype(np.int32))

    def build(sharded: bool):
        init, step, compute = make_step(
            AUROC(sample_capacity=cap),
            axis_name="dp",
            with_value=False,
            sharded_state=sharded,
        )

        def prog(p, t):
            state, _ = step(init(), p, t)
            return compute(state)

        return jax.jit(_shard_map(prog, mesh, in_specs=(P("dp"), P("dp")), out_specs=P()))

    rep = build(False)
    shd = build(True)
    want = float(rep(preds, target))
    got = float(shd(preds, target))
    assert abs(want - got) < 1e-5, f"sharded AUROC diverged: {got} vs {want}"
    out["replicated_auroc_1M_sync_ms"] = _best_ms(lambda: rep(preds, target), trials=3)
    out["sharded_auroc_1M_sync_ms"] = _best_ms(lambda: shd(preds, target), trials=3)

    # --- hierarchical vs flat reduction ---------------------------------
    mesh2 = Mesh(np.asarray(jax.devices()[:n_dev]).reshape(2, n_dev // 2), ("dcn", "ici"))
    state = jnp.asarray(rng.random(n_dev * N_SAMPLES // n_dev, dtype=np.float32))

    def flat(v):
        return jax.lax.psum(v, ("ici", "dcn")).sum()

    def hier(v):
        return jax.lax.psum(jax.lax.psum(v, "ici"), "dcn").sum()

    spec = P(("dcn", "ici"))
    f_flat = jax.jit(_shard_map(flat, mesh2, in_specs=(spec,), out_specs=P()))
    f_hier = jax.jit(_shard_map(hier, mesh2, in_specs=(spec,), out_specs=P()))
    flat_ms = _best_ms(lambda: f_flat(state))
    hier_ms = _best_ms(lambda: f_hier(state))
    out["hier_reduce_vs_flat_ratio"] = hier_ms / flat_ms if flat_ms > 0 else float("nan")
    return out


def measure_prefetch() -> dict:
    """``epoch_prefetch_overlap_pct`` — single-device, host-resident epoch."""
    import jax
    import jax.numpy as jnp  # noqa: F401
    import numpy as np

    from metrics_tpu import Accuracy, make_epoch

    rng = np.random.default_rng(1)
    batch = N_SAMPLES // N_BATCHES
    pe = rng.integers(0, 10, (N_BATCHES, batch)).astype(np.int32)
    te = rng.integers(0, 10, (N_BATCHES, batch)).astype(np.int32)
    k = 2

    init_p, epoch_p, _ = make_epoch(Accuracy, num_classes=10, prefetch=k)
    init_s, epoch_s, _ = make_epoch(Accuracy, num_classes=10)

    def overlapped():
        state, _ = epoch_p(init_p(), pe, te)
        return state

    def serialized():
        # the same chunked program with every transfer and fold serialized:
        # device_put blocks, then the fold blocks — zero overlap by
        # construction, so the delta IS the recovered transfer time
        state = init_s()
        for lo in range(0, N_BATCHES, k):
            chunk_p = jax.block_until_ready(jax.device_put(pe[lo : lo + k]))
            chunk_t = jax.block_until_ready(jax.device_put(te[lo : lo + k]))
            state, _ = epoch_s(state, chunk_p, chunk_t)
            state = jax.block_until_ready(state)
        return state

    t_serial = _best_ms(serialized)
    t_overlap = _best_ms(overlapped)
    pct = 100.0 * (t_serial - t_overlap) / t_serial if t_serial > 0 else float("nan")
    # the row pipeline (emit guard + rows_by_metric) requires positive
    # values, but zero/negative overlap is REAL signal — a prefetch
    # regression must not vanish as a skipped row. Floor at 0.01%: the
    # published value still reads "no measurable overlap", and the
    # inverted gate fires against any prior round that recorded a real win.
    if pct == pct:  # not NaN
        pct = max(pct, 0.01)
    return {
        "epoch_prefetch_overlap_pct": pct,
        "epoch_prefetch_serial_ms": t_serial,
        "epoch_prefetch_overlap_ms": t_overlap,
    }


if __name__ == "__main__":
    # self-provision an 8-device virtual CPU mesh (must run pre-import,
    # which is why single-device hosts reach this via a subprocess)
    import os
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"
    # OVERRIDE any inherited device-count flag (a parent pinning it to 1
    # for determinism would otherwise leave this subprocess single-device
    # and the mesh rows would silently vanish from the sweep)
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "", os.environ.get("XLA_FLAGS", "")
    ).strip()
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    print(json.dumps(measure()))
