"""COCO mAP compute at 2k images (BASELINE.md config).

The evaluation is host-side (greedy COCO matching is sequential over
score-ranked detections) but vectorized over the IoU-threshold axis and
grouped with one lexsort pass; this times the full ``compute()`` on
accumulated flat-buffer state."""
import json
import time

import numpy as np

from metrics_tpu import MeanAveragePrecision

N_IMAGES, MAX_BOXES, N_CLASSES = 2_000, 15, 10


def make_inputs(n_images: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    preds, targets = [], []
    for _ in range(n_images):
        nd, ng = rng.integers(1, MAX_BOXES), rng.integers(1, MAX_BOXES)
        xy = rng.uniform(0, 200, (nd, 2))
        gxy = rng.uniform(0, 200, (ng, 2))
        # host numpy inputs: on a tunneled TPU, per-image device round
        # trips in validation would dominate setup
        preds.append(
            dict(
                boxes=np.concatenate([xy, xy + rng.uniform(5, 80, (nd, 2))], 1).astype(np.float32),
                scores=rng.uniform(0, 1, nd).astype(np.float32),
                labels=rng.integers(0, N_CLASSES, nd).astype(np.int32),
            )
        )
        targets.append(
            dict(
                boxes=np.concatenate([gxy, gxy + rng.uniform(5, 80, (ng, 2))], 1).astype(np.float32),
                labels=rng.integers(0, N_CLASSES, ng).astype(np.int32),
            )
        )
    return preds, targets


def measure(n_images: int = N_IMAGES, n_trials: int = 3) -> float:
    preds, targets = make_inputs(n_images)
    metric = MeanAveragePrecision()
    for i in range(0, n_images, 100):
        metric.update(preds[i : i + 100], targets[i : i + 100])
    metric.compute()  # warm caches
    times = []
    for _ in range(n_trials):
        metric._computed = None
        t0 = time.perf_counter()
        metric.compute()
        times.append(time.perf_counter() - t0)
    return min(times) * 1000


def measure_pycocotools(n_images: int = N_IMAGES) -> float:
    """Optional honest baseline: pycocotools' C `accumulate` on the same corpus.

    The plain-loop oracle (benchmarks/map_oracle.py) is a Python COCO
    protocol loop; pycocotools runs its accumulate in C, so it is the
    fair reference-speed target. Returns NaN when not installed.
    """
    try:
        from pycocotools.coco import COCO
        from pycocotools.cocoeval import COCOeval
    except ImportError:
        return float("nan")
    preds, targets = make_inputs(n_images)
    images, anns, dets = [], [], []
    ann_id = 1
    for i, (p, t) in enumerate(zip(preds, targets)):
        images.append(dict(id=i))
        for b, l in zip(t["boxes"], t["labels"]):
            anns.append(
                dict(id=ann_id, image_id=i, category_id=int(l), iscrowd=0,
                     area=float((b[2] - b[0]) * (b[3] - b[1])),
                     bbox=[float(b[0]), float(b[1]), float(b[2] - b[0]), float(b[3] - b[1])])
            )
            ann_id += 1
        for b, s, l in zip(p["boxes"], p["scores"], p["labels"]):
            dets.append(
                dict(image_id=i, category_id=int(l), score=float(s),
                     bbox=[float(b[0]), float(b[1]), float(b[2] - b[0]), float(b[3] - b[1])])
            )
    gt = COCO()
    gt.dataset = dict(images=images, annotations=anns,
                      categories=[dict(id=c) for c in range(N_CLASSES)])
    gt.createIndex()
    dt = gt.loadRes(dets)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        ev = COCOeval(gt, dt, iouType="bbox")
        ev.evaluate()
        ev.accumulate()
        times.append(time.perf_counter() - t0)
    return min(times) * 1000


def main() -> None:
    ms = measure()
    print(json.dumps({"metric": "detection_map_2k_images_compute", "value": round(ms, 1), "unit": "ms"}))
    pyc = measure_pycocotools()
    if pyc == pyc:  # not NaN
        print(json.dumps({"metric": "detection_map_2k_images_pycocotools_baseline", "value": round(pyc, 1), "unit": "ms"}))
    else:
        print(json.dumps({"metric": "detection_map_2k_images_pycocotools_baseline", "value": None, "unit": "ms", "note": "pycocotools not installed"}))


if __name__ == "__main__":
    main()
