"""COCO mAP compute at 2k images (BASELINE.md config).

The evaluation is host-side (greedy COCO matching is sequential over
score-ranked detections) but vectorized over the IoU-threshold axis and
grouped with one lexsort pass; this times the full ``compute()`` on
accumulated flat-buffer state."""
import json
import time

import numpy as np

from metrics_tpu import MeanAveragePrecision

N_IMAGES, MAX_BOXES, N_CLASSES = 2_000, 15, 10


def make_inputs(n_images: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    preds, targets = [], []
    for _ in range(n_images):
        nd, ng = rng.integers(1, MAX_BOXES), rng.integers(1, MAX_BOXES)
        xy = rng.uniform(0, 200, (nd, 2))
        gxy = rng.uniform(0, 200, (ng, 2))
        # host numpy inputs: on a tunneled TPU, per-image device round
        # trips in validation would dominate setup
        preds.append(
            dict(
                boxes=np.concatenate([xy, xy + rng.uniform(5, 80, (nd, 2))], 1).astype(np.float32),
                scores=rng.uniform(0, 1, nd).astype(np.float32),
                labels=rng.integers(0, N_CLASSES, nd).astype(np.int32),
            )
        )
        targets.append(
            dict(
                boxes=np.concatenate([gxy, gxy + rng.uniform(5, 80, (ng, 2))], 1).astype(np.float32),
                labels=rng.integers(0, N_CLASSES, ng).astype(np.int32),
            )
        )
    return preds, targets


def measure(n_images: int = N_IMAGES, n_trials: int = 3) -> float:
    preds, targets = make_inputs(n_images)
    metric = MeanAveragePrecision()
    for i in range(0, n_images, 100):
        metric.update(preds[i : i + 100], targets[i : i + 100])
    metric.compute()  # warm caches
    times = []
    for _ in range(n_trials):
        metric._computed = None
        t0 = time.perf_counter()
        metric.compute()
        times.append(time.perf_counter() - t0)
    return min(times) * 1000


def main() -> None:
    ms = measure()
    print(json.dumps({"metric": "detection_map_2k_images_compute", "value": round(ms, 1), "unit": "ms"}))


if __name__ == "__main__":
    main()
