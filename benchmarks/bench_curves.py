"""AUROC at 1M accumulated samples (BASELINE.md config): exact (sort-based)
and binned (pallas threshold kernel) variants."""
import json

import jax
import jax.numpy as jnp

from benchmarks._timing import measure_ms_scaled
from metrics_tpu.functional.classification.auroc import _auroc_compute
from metrics_tpu.utilities.enums import DataType
from metrics_tpu.ops import binned_counts

N, T, K = 1_000_000, 100, 100  # K large enough that K epochs >> one dispatch RTT


def measure() -> dict:
    preds = jax.random.uniform(jax.random.PRNGKey(0), (N,))
    target = (jax.random.uniform(jax.random.PRNGKey(1), (N,)) > 0.5).astype(jnp.int32)

    # the eager value-validation gate is host-side by design; jit the
    # sort-based compute kernel itself
    exact = jax.jit(lambda p, t: _auroc_compute(p, t, DataType.BINARY, pos_label=1))

    def make_exact(k):
        @jax.jit
        def run(preds=preds, target=target):
            def body(i, acc):
                return acc + exact(preds + 0.0001 * i, target)
            return jax.lax.fori_loop(0, k, body, jnp.zeros(()))
        return run

    out = {}
    out["auroc_exact_1M_compute"] = measure_ms_scaled(make_exact, K)

    thresholds = jnp.linspace(0, 1.0, T)

    def make_binned(k):
        @jax.jit
        def run(preds=preds, target=target):
            def body(i, acc):
                tps, fps, fns = binned_counts(
                    (preds + 0.0001 * i).reshape(-1, 1), target.reshape(-1, 1), thresholds
                )
                return acc + tps.sum()
            return jax.lax.fori_loop(0, k, body, jnp.zeros(()))
        return run

    out["binned_counts_1M_T100_update"] = measure_ms_scaled(make_binned, K)
    return out


def main() -> None:
    for name, ms in measure().items():
        print(json.dumps({"metric": name, "value": round(ms, 3), "unit": "ms"}))


if __name__ == "__main__":
    main()
