"""Shared timing for benchmarks: in-jit repetition + paired-K differencing
+ bimodal-mode clustering.

Tunneled TPU setups add a host<->device round-trip per dispatch whose
latency swings between ~20 us and ~90 ms phases (sometimes seconds). Every
benchmark repeats its workload K times inside one jit and again at 2K; the
estimator INTERLEAVES the K and 2K trials and differences each adjacent
pair, so both sides of every difference see the same RTT phase and the
dispatch cost cancels per pair.

On top of the RTT noise, the chip itself has a BIMODAL ~1.9x performance
state that flips between processes AND within a session (measured round 4,
benchmarks/RESULTS.md) — slower but otherwise healthy execution, which no
amount of pair differencing removes. A single published number is therefore
whichever mode the sweep happened to hit, and round-over-round comparisons
were confounded. The fix: keep every per-pair sample, CLUSTER the samples
at the largest consecutive gap (modes are ~1.9x apart; a 1.35x split
threshold separates them while absorbing ordinary jitter), and publish
``{fast_mode_median, slow_mode_median, n_fast, n_slow}``. The headline
value is the FAST-mode median — the chip's actual capability — and the
regression gate compares fast mode against fast mode.

If no consistent sample cluster exists (phase noise exceeded the workload
entirely), the measurement is NaN rather than a fabricated number, and
``measure_ms_scaled`` doubles K until the workload swamps the noise.
"""
import math
import time
from typing import Callable, List, Optional

import jax

from metrics_tpu.utilities.compile_cache import enable_persistent_cache

enable_persistent_cache()


class ModalMs(float):
    """A per-repeat milliseconds estimate that carries its mode statistics.

    The float value IS the fast-mode median, so existing consumers keep
    working; ``slow_mode_median`` is None when every sample landed in one
    mode.
    """

    fast_mode_median: float
    slow_mode_median: Optional[float]
    n_fast: int
    n_slow: int

    def __new__(cls, fast: float, slow: Optional[float], n_fast: int, n_slow: int) -> "ModalMs":
        self = super().__new__(cls, fast)
        self.fast_mode_median = fast
        self.slow_mode_median = slow
        self.n_fast = n_fast
        self.n_slow = n_slow
        return self


def _median(sorted_vals: List[float]) -> float:
    mid = len(sorted_vals) // 2
    if len(sorted_vals) % 2:
        return sorted_vals[mid]
    return 0.5 * (sorted_vals[mid - 1] + sorted_vals[mid])


# modes sit ~1.9x apart; a split threshold halfway (geometrically) between
# ordinary jitter and the mode ratio separates them reliably
_MODE_SPLIT_RATIO = 1.35
# samples beyond this band around the MEDIAN are mid-pair phase flips /
# dispatch stalls / differencing undershoots, not a mode (the mode ratio is
# ~1.9, comfortably inside the band whichever mode holds the median)
_OUTLIER_RATIO = 2.5


def _cluster_modes(samples: List[float]) -> Optional[ModalMs]:
    """Split per-pair samples into the two known chip modes.

    Returns None (-> caller escalates K) when the samples cannot support a
    trustworthy estimate. A LONE low sample is rejected rather than
    published: pair differencing occasionally undershoots (a phase flip
    mid-pair), and min-selection over that noise is biased low — a real
    fast mode shows at least two agreeing samples.
    """
    if not samples:
        return None
    s = sorted(samples)
    # anchor at the SMALLEST sample that has a partner agreeing within the
    # mode-split ratio: a lone minimum is differencing undershoot, but two
    # agreeing small samples are real — and anchoring there (not at the
    # median) keeps a true fast mode even when slow-phase samples are the
    # majority. Samples beyond the outlier band of the anchor are dispatch
    # stalls (the real slow mode at ~1.9x sits inside the band).
    anchor = next((s[i] for i in range(len(s) - 1) if s[i + 1] <= _MODE_SPLIT_RATIO * s[i]), None)
    if anchor is None:
        return None  # no two samples agree: nothing trustworthy to publish
    s = [d for d in s if anchor <= d <= anchor * _OUTLIER_RATIO]
    while len(s) >= 2:
        if s[-1] <= _MODE_SPLIT_RATIO * s[0]:
            return ModalMs(_median(s), None, len(s), 0)
        cut = max(range(1, len(s)), key=lambda i: s[i] / s[i - 1])
        if cut == 1:
            if len(s) == 2:
                return None  # two disagreeing samples decide nothing
            s = s[1:]  # lone low sample: differencing undershoot, drop
            continue
        return ModalMs(_median(s[:cut]), _median(s[cut:]), cut, len(s) - cut)
    return None


def measure_ms(
    run: Callable[[], jax.Array],
    k_repeats: int,
    n_timing: int = 10,
    run_double: Callable[[], jax.Array] = None,
) -> float:
    """Wall-clock ms per repeat: interleaved ``(T(2K) - T(K)) / K`` pairs.

    Returns a :class:`ModalMs` (fast-mode median + mode stats) or NaN when
    no pair produced a usable difference (dispatch-phase noise larger than
    the whole workload).
    """
    if run_double is None:
        raise TypeError("measure_ms requires run_double (the 2K-repeat thunk)")
    float(run())  # warmup + compile
    float(run_double())
    samples = []
    for _ in range(n_timing):
        t0 = time.perf_counter()
        float(run())
        t1 = time.perf_counter()
        float(run_double())
        t2 = time.perf_counter()
        diff = (t2 - t1) - (t1 - t0)
        if diff > 0:
            samples.append(diff / k_repeats * 1000.0)
    out = _cluster_modes(samples)
    return math.nan if out is None else out


def measure_ms_scaled(
    make_run: Callable[[int], Callable[[], jax.Array]],
    k_repeats: int,
    n_timing: int = 10,
    max_doublings: int = 3,
) -> float:
    """``measure_ms`` with automatic K escalation.

    ``make_run(k)`` builds the K-repeat thunk. When clustering fails (RTT
    phase noise bigger than the whole K-repeat workload), K doubles —
    growing the workload until it swamps the noise — up to
    ``max_doublings`` times before conceding NaN.
    """
    k = k_repeats
    for _ in range(max_doublings + 1):
        ms = measure_ms(make_run(k), k, n_timing=n_timing, run_double=make_run(2 * k))
        if not math.isnan(ms):
            return ms
        k *= 2
    return math.nan


def cluster_direct_samples(samples: List[float]) -> Optional[ModalMs]:
    """Mode stats for DIRECT wall-clock samples (no pair differencing).

    A completed wall-clock measurement cannot undershoot — the work
    physically finished — so unlike :func:`_cluster_modes` the fast cluster
    anchors at the MINIMUM: samples within the outlier band of it are the
    fast phase (e.g. fast tunnel-RTT calls), the rest the slow phase.

    Anchoring still needs agreement: with the 20us-90ms RTT swing, ONE lucky
    fast-phase call out of 8-10 is not a mode, and publishing it would make
    direct rows (WER, probe_tunnel_rtt) round-over-round noisy in exactly
    the way this protocol exists to avoid. The minimum only anchors the
    fast cluster when a second sample agrees within the mode-split ratio;
    otherwise the overall median is published (single mode, no split).
    """
    if not samples:
        return None
    s = sorted(samples)
    if len(s) >= 2 and s[1] <= _MODE_SPLIT_RATIO * s[0]:
        fast = [d for d in s if d <= _OUTLIER_RATIO * s[0]]
        slow = [d for d in s if d > _OUTLIER_RATIO * s[0]]
        return ModalMs(_median(fast), _median(slow) if slow else None, len(fast), len(slow))
    return ModalMs(_median(s), None, len(s), 0)
