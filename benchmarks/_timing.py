"""Shared timing for benchmarks: in-jit repetition + RTT subtraction.

Tunneled TPU setups add ~65 ms of host<->device round-trip per dispatch;
every benchmark therefore repeats its workload K times inside one jit and
subtracts the measured null-dispatch round-trip (same approach as the
top-level bench.py).
"""
import os
import time
from typing import Callable

import jax
import jax.numpy as jnp

# Persistent XLA compilation cache: the big sort/segment kernels at 1M
# samples cost minutes of compile on a cold process; cached executables cut
# repeat bench runs to the actual device time.
_CACHE_DIR = os.path.join(os.path.expanduser("~"), ".cache", "metrics_tpu_xla")
try:
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:  # older jax without the knob: cold compiles only
    pass


def measure_ms(run: Callable[[], jax.Array], k_repeats: int, n_timing: int = 12) -> float:
    """Wall-clock ms per repeat for ``run`` (a jitted thunk doing K repeats)."""
    float(run())  # warmup + compile
    times = []
    for _ in range(n_timing):
        t0 = time.perf_counter()
        float(run())
        times.append(time.perf_counter() - t0)
    null = jax.jit(lambda x: x + 1.0)
    float(null(jnp.zeros(())))
    null_times = []
    for _ in range(n_timing):
        t0 = time.perf_counter()
        float(null(jnp.zeros(())))
        null_times.append(time.perf_counter() - t0)
    rtt = min(null_times)
    best = min(times)
    if rtt >= best:
        rtt = 0.0
    return (best - rtt) / k_repeats * 1000.0
