"""Shared timing for benchmarks: in-jit repetition + paired-K differencing.

Tunneled TPU setups add a host<->device round-trip per dispatch whose
latency swings between ~20 us and ~90 ms phases (sometimes seconds). Every
benchmark repeats its workload K times inside one jit and again at 2K; the
estimator INTERLEAVES the K and 2K trials and differences each adjacent
pair, so both sides of every difference see the same RTT phase and the
dispatch cost cancels per pair. The smallest non-negative pair difference
is the per-K estimate; if every pair is negative (phase noise exceeded the
workload entirely), the measurement is reported as NaN rather than a
fabricated number.
"""
import math
import time
from typing import Callable

import jax

from metrics_tpu.utilities.compile_cache import enable_persistent_cache

enable_persistent_cache()


def measure_ms(
    run: Callable[[], jax.Array],
    k_repeats: int,
    n_timing: int = 8,
    run_double: Callable[[], jax.Array] = None,
) -> float:
    """Wall-clock ms per repeat: interleaved ``(T(2K) - T(K)) / K`` pairs.

    ``run`` executes the workload K times inside one jit, ``run_double`` the
    same workload 2K times. Returns NaN when no pair produced a usable
    difference (dispatch-phase noise larger than the whole workload).
    """
    if run_double is None:
        raise TypeError("measure_ms requires run_double (the 2K-repeat thunk)")
    float(run())  # warmup + compile
    float(run_double())
    diffs = []
    for _ in range(n_timing):
        t0 = time.perf_counter()
        float(run())
        t1 = time.perf_counter()
        float(run_double())
        t2 = time.perf_counter()
        diffs.append((t2 - t1) - (t1 - t0))
    usable = sorted(d for d in diffs if d > 0)
    # consistency gate: trust the estimate only when the two smallest
    # positive pairs agree within 2x — random noise differences are
    # continuous and almost never produce two small near-equal positives,
    # while genuine workload differences cluster tightly
    if len(usable) < 2 or usable[1] > 2.0 * usable[0]:
        return math.nan
    return usable[0] / k_repeats * 1000.0
