"""Shared timing for benchmarks: in-jit repetition + paired-K differencing.

Tunneled TPU setups add a host<->device round-trip per dispatch whose
latency swings between ~20 us and ~90 ms phases (sometimes seconds). Every
benchmark repeats its workload K times inside one jit and again at 2K; the
estimator INTERLEAVES the K and 2K trials and differences each adjacent
pair, so both sides of every difference see the same RTT phase and the
dispatch cost cancels per pair. The estimate is the MEDIAN of the positive
pair differences that pass a consistency gate (min-selection over noisy
differences is biased low — it would flatter vs_baseline ratios); if no
consistent pair cluster exists (phase noise exceeded the workload
entirely), the measurement is NaN rather than a fabricated number, and
``measure_ms_scaled`` doubles K until the workload swamps the noise.
"""
import math
import time
from typing import Callable

import jax

from metrics_tpu.utilities.compile_cache import enable_persistent_cache

enable_persistent_cache()


def measure_ms(
    run: Callable[[], jax.Array],
    k_repeats: int,
    n_timing: int = 8,
    run_double: Callable[[], jax.Array] = None,
) -> float:
    """Wall-clock ms per repeat: interleaved ``(T(2K) - T(K)) / K`` pairs.

    ``run`` executes the workload K times inside one jit, ``run_double`` the
    same workload 2K times. Returns NaN when no pair produced a usable
    difference (dispatch-phase noise larger than the whole workload).
    """
    if run_double is None:
        raise TypeError("measure_ms requires run_double (the 2K-repeat thunk)")
    float(run())  # warmup + compile
    float(run_double())
    diffs = []
    for _ in range(n_timing):
        t0 = time.perf_counter()
        float(run())
        t1 = time.perf_counter()
        float(run_double())
        t2 = time.perf_counter()
        diffs.append((t2 - t1) - (t1 - t0))
    usable = sorted(d for d in diffs if d > 0)
    # consistency gate: trust the estimate only when the two smallest
    # positive pairs agree within 2x — random noise differences are
    # continuous and almost never produce two small near-equal positives,
    # while genuine workload differences cluster tightly
    if len(usable) < 2 or usable[1] > 2.0 * usable[0]:
        return math.nan
    # median of the gated cluster (pairs within 2x of the smallest), not the
    # raw min: min-selection over noisy differences is biased low
    cluster = [d for d in usable if d <= 2.0 * usable[0]]
    mid = len(cluster) // 2
    median = cluster[mid] if len(cluster) % 2 else 0.5 * (cluster[mid - 1] + cluster[mid])
    return median / k_repeats * 1000.0


def measure_ms_scaled(
    make_run: Callable[[int], Callable[[], jax.Array]],
    k_repeats: int,
    n_timing: int = 8,
    max_doublings: int = 3,
) -> float:
    """``measure_ms`` with automatic K escalation.

    ``make_run(k)`` builds the K-repeat thunk. When the consistency gate
    rejects a measurement (RTT phase noise bigger than the whole K-repeat
    workload), K doubles — growing the workload until it swamps the noise —
    up to ``max_doublings`` times before conceding NaN.
    """
    k = k_repeats
    for _ in range(max_doublings + 1):
        ms = measure_ms(make_run(k), k, n_timing=n_timing, run_double=make_run(2 * k))
        if not math.isnan(ms):
            return ms
        k *= 2
    return math.nan
