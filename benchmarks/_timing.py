"""Shared timing for benchmarks: in-jit repetition + RTT subtraction.

Tunneled TPU setups add ~65 ms of host<->device round-trip per dispatch;
every benchmark therefore repeats its workload K times inside one jit and
subtracts the measured null-dispatch round-trip (same approach as the
top-level bench.py).
"""
import time
from typing import Callable

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.compile_cache import enable_persistent_cache

enable_persistent_cache()


def measure_ms(run: Callable[[], jax.Array], k_repeats: int, n_timing: int = 12) -> float:
    """Wall-clock ms per repeat for ``run`` (a jitted thunk doing K repeats)."""
    float(run())  # warmup + compile
    times = []
    for _ in range(n_timing):
        t0 = time.perf_counter()
        float(run())
        times.append(time.perf_counter() - t0)
    null = jax.jit(lambda x: x + 1.0)
    float(null(jnp.zeros(())))
    null_times = []
    for _ in range(n_timing):
        t0 = time.perf_counter()
        float(null(jnp.zeros(())))
        null_times.append(time.perf_counter() - t0)
    rtt = min(null_times)
    best = min(times)
    if rtt >= best:
        rtt = 0.0
    return (best - rtt) / k_repeats * 1000.0
