"""Independent plain-loop COCO mAP evaluator.

The honest baseline for the detection benchmark and the fuzz oracle for
``tests/detection/test_map.py`` (the reference pins against pycocotools,
``/root/reference/tests/detection/test_map.py``; that package is often
unavailable offline, so this is a from-scratch implementation of the same
protocol). Lives in benchmarks/ so ``bench.py`` does not depend on the test
tree's module layout.
"""
import numpy as np

IOU_THRS = np.linspace(0.5, 0.95, 10)
REC_THRS = np.linspace(0.0, 1.0, 101)
AREA_RANGES = {
    "all": (0, int(1e10)),
    "small": (0, 32**2),
    "medium": (32**2, 96**2),
    "large": (96**2, int(1e10)),
}
MAX_DETS = [1, 10, 100]


def _iou(d, g):
    lt = np.maximum(d[:, None, :2], g[None, :, :2])
    rb = np.minimum(d[:, None, 2:], g[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    a_d = (d[:, 2] - d[:, 0]) * (d[:, 3] - d[:, 1])
    a_g = (g[:, 2] - g[:, 0]) * (g[:, 3] - g[:, 1])
    union = a_d[:, None] + a_g[None, :] - inter
    return np.where(union > 0, inter / np.where(union > 0, union, 1), 0.0)


def _oracle_eval_img(det, scores, gt, area_range, max_det):
    """Plain-loop per-image, per-class evaluation (thresholds x dets loops)."""
    if len(gt) == 0 and len(det) == 0:
        return None
    areas = (gt[:, 2] - gt[:, 0]) * (gt[:, 3] - gt[:, 1])
    ignore = (areas < area_range[0]) | (areas > area_range[1])
    gtind = np.argsort(ignore, kind="stable")
    gt, gt_ignore = gt[gtind], ignore[gtind]
    order = np.argsort(-scores, kind="stable")[:max_det]
    det, scores = det[order], scores[order]
    ious = _iou(det, gt)

    T, D, G = len(IOU_THRS), len(det), len(gt)
    dtm = np.zeros((T, D), bool)
    gtm = np.zeros((T, G), bool)
    dti = np.zeros((T, D), bool)
    for ti, thr in enumerate(IOU_THRS):
        for di in range(D):
            vals = ious[di] * ~(gtm[ti] | gt_ignore)
            if G == 0:
                continue
            m = int(vals.argmax())
            if vals[m] > thr:
                dtm[ti, di] = True
                gtm[ti, m] = True
                dti[ti, di] = gt_ignore[m]
    if D:
        det_areas = (det[:, 2] - det[:, 0]) * (det[:, 3] - det[:, 1])
        out = (det_areas < area_range[0]) | (det_areas > area_range[1])
        dti = dti | (~dtm & out[None, :])
    return dict(dtm=dtm, gtm=gtm, scores=scores, gti=gt_ignore, dti=dti)


def _oracle_map(preds, targets, class_metrics=False):
    """Full plain-loop COCO evaluation over a corpus of per-image dicts."""
    classes = sorted(
        set(np.concatenate([np.asarray(p["labels"]).reshape(-1) for p in preds] +
                           [np.asarray(t["labels"]).reshape(-1) for t in targets]).astype(int).tolist())
        if preds or targets else []
    )
    n_imgs = len(preds)
    K, A, M, T, R = len(classes), len(AREA_RANGES), len(MAX_DETS), len(IOU_THRS), len(REC_THRS)
    precision = -np.ones((T, R, K, A, M))
    recall = -np.ones((T, K, A, M))

    for ki, cls in enumerate(classes):
        for ai, area_range in enumerate(AREA_RANGES.values()):
            evals = []
            for i in range(n_imgs):
                d_lab = np.asarray(preds[i]["labels"]).reshape(-1)
                g_lab = np.asarray(targets[i]["labels"]).reshape(-1)
                d_m, g_m = d_lab == cls, g_lab == cls
                if not d_m.any() and not g_m.any():
                    evals.append(None)
                    continue
                det = np.asarray(preds[i]["boxes"], float).reshape(-1, 4)[d_m]
                sc = np.asarray(preds[i]["scores"], float).reshape(-1)[d_m]
                gt = np.asarray(targets[i]["boxes"], float).reshape(-1, 4)[g_m]
                evals.append(_oracle_eval_img(det, sc, gt, area_range, MAX_DETS[-1]))
            evals = [e for e in evals if e is not None]
            if not evals:
                continue
            for mi, max_det in enumerate(MAX_DETS):
                scores = np.concatenate([e["scores"][:max_det] for e in evals])
                inds = np.argsort(-scores, kind="mergesort")
                dtm = np.concatenate([e["dtm"][:, :max_det] for e in evals], 1)[:, inds]
                dti = np.concatenate([e["dti"][:, :max_det] for e in evals], 1)[:, inds]
                gti = np.concatenate([e["gti"] for e in evals])
                npig = int((~gti).sum())
                if npig == 0:
                    continue
                tps = np.cumsum(dtm & ~dti, 1, dtype=float)
                fps = np.cumsum(~dtm & ~dti, 1, dtype=float)
                for ti in range(T):
                    tp, fp = tps[ti], fps[ti]
                    nd = len(tp)
                    rc = tp / npig
                    pr = tp / (fp + tp + np.finfo(float).eps)
                    recall[ti, ki, ai, mi] = rc[-1] if nd else 0
                    # right-max envelope via the reference's iterative lift
                    pr = pr.copy()
                    while True:
                        diff = np.clip(np.concatenate([pr[1:] - pr[:-1], [0.0]]), 0, None)
                        if np.all(diff == 0):
                            break
                        pr += diff
                    idxs = np.searchsorted(rc, REC_THRS, side="left")
                    num = int(idxs.argmax()) if idxs.max() >= nd else R
                    row = np.zeros(R)
                    row[:num] = pr[idxs[:num]]
                    precision[ti, :, ki, ai, mi] = row

    def summ(arr, avg_prec, thr=None, area="all", max_det=100):
        ai = list(AREA_RANGES).index(area)
        mi = MAX_DETS.index(max_det)
        x = arr[..., ai, mi]
        if thr is not None:
            x = x[list(IOU_THRS).index(thr)]
        v = x[x > -1]
        return float(v.mean()) if v.size else -1.0

    out = {
        "map": summ(precision, True),
        "map_50": summ(precision, True, 0.5),
        "map_75": summ(precision, True, 0.75),
        "map_small": summ(precision, True, area="small"),
        "map_medium": summ(precision, True, area="medium"),
        "map_large": summ(precision, True, area="large"),
        "mar_1": summ(recall, False, max_det=1),
        "mar_10": summ(recall, False, max_det=10),
        "mar_100": summ(recall, False, max_det=100),
        "mar_small": summ(recall, False, area="small"),
        "mar_medium": summ(recall, False, area="medium"),
        "mar_large": summ(recall, False, area="large"),
    }
    if class_metrics:
        out["map_per_class"] = [
            summ(precision[:, :, k : k + 1], True) for k in range(K)
        ]
        out["mar_100_per_class"] = [summ(recall[:, k : k + 1], False) for k in range(K)]
    return out


