"""RetrievalMAP / RetrievalNormalizedDCG at MSLR scale (BASELINE.md config).

10k queries x 100 docs = 1M documents, scored in the fused lexsort +
segment-op kernel the retrieval domain compiles to (replacing the
reference's per-query Python dict loop, reference
``utilities/data.py:196-220`` + ``retrieval/base.py:128-141``).
"""
import json

import jax
import jax.numpy as jnp

from benchmarks._timing import measure_ms_scaled
from metrics_tpu.retrieval import RetrievalMAP, RetrievalNormalizedDCG

N_QUERIES, DOCS, K = 10_000, 100, 10
TOP_K, K_TOPK = 10, 40  # @k row is ~4x faster; K scales to keep ~40 ms trials
N = N_QUERIES * DOCS


def measure() -> dict:
    out = {}
    preds = jax.random.uniform(jax.random.PRNGKey(0), (N,))
    target = (jax.random.uniform(jax.random.PRNGKey(1), (N,)) > 0.9).astype(jnp.int32)
    indexes = jnp.repeat(jnp.arange(N_QUERIES), DOCS)

    for name, cls in (("retrieval_map", RetrievalMAP), ("retrieval_ndcg", RetrievalNormalizedDCG)):
        metric = cls()
        metric.update(preds, target, indexes=indexes)
        p, t, i = metric.preds[0], metric.target[0], metric.indexes[0]
        compute_kernel = jax.jit(
            lambda p, t, i, m=metric: _compute_once(m, p, t, i)
        )

        def make_run(k, p=p, t=t, i=i, kern=compute_kernel):
            @jax.jit
            def run(p=p, t=t, i=i):
                def body(j, acc):
                    return acc + kern(p * (1.0 + 0.0001 * j), t, i)
                return jax.lax.fori_loop(0, k, body, jnp.zeros(()))
            return run

        out[f"{name}_1M_docs_compute"] = measure_ms_scaled(make_run, K)

    # MAP@k=10 over the same 1M docs: the segment-local top-k path — one
    # per-query lax.top_k over the dense (Q, D) view plus (Q, k) row math,
    # no full multi-operand sort (see functional/retrieval/_segment.py)
    metric10 = RetrievalMAP(k=TOP_K)
    metric10.update(preds, target, indexes=indexes)
    p, t = metric10.preds[0], metric10.target[0]
    topk_kernel = jax.jit(
        lambda p, t, m=metric10: _compute_topk_once(m, p, t, (N_QUERIES, DOCS))
    )

    def make_run_topk(k, p=p, t=t, kern=topk_kernel):
        @jax.jit
        def run(p=p, t=t):
            def body(j, acc):
                return acc + kern(p * (1.0 + 0.0001 * j), t)
            return jax.lax.fori_loop(0, k, body, jnp.zeros(()))
        return run

    out["retrieval_map_k10_1M_docs_compute"] = measure_ms_scaled(make_run_topk, K_TOPK)
    return out


def main() -> None:
    for name, ms in measure().items():
        print(json.dumps({"metric": name, "value": round(ms, 3), "unit": "ms"}))


def _compute_once(metric, preds, target, indexes):
    from metrics_tpu.functional.retrieval._segment import make_group_context

    ctx = make_group_context(preds, target, indexes)
    scores = metric._metric_vectorized(ctx)
    valid = metric._valid_groups(ctx)
    keep = ctx.nonempty & valid
    return jnp.where(keep, scores, 0.0).sum() / jnp.maximum(keep.sum(), 1)


def _compute_topk_once(metric, preds, target, shape):
    from metrics_tpu.functional.retrieval._segment import make_topk_context

    tctx = make_topk_context(preds, target, shape, metric.k)
    scores = metric._metric_topk(tctx)
    valid = metric._valid_groups_topk(tctx)
    return jnp.where(valid, scores, 0.0).sum() / jnp.maximum(valid.sum(), 1)


if __name__ == "__main__":
    main()
