"""Large-state mesh sync cost: a 1M-sample CapacityBuffer gathered over 8 devices.

Measures ``sync_buffer_in_context`` — the in-graph analogue of the
reference's uneven cat-state gather (``torchmetrics/utilities/
distributed.py:128-151``) — on a 1M-sample float32 buffer (125k samples x 8
devices), comparing the two gather typings:

* ``invariant``: psum of a zero-padded scatter (replicated-typed output,
  satisfies ``out_specs=P()`` directly) — an all-reduce over ``n_dev x``
  payload, ~2x an all-gather's bytes on a ring plus the zero-buffer
  materialization.
* ``varying``: native ``lax.all_gather`` at 1x payload; invariant typing is
  restored on the small FINAL value with ``replicate_typed`` (a scalar pmax).

Both the static-count regime (one traced program; the gather moves only the
filled prefix) and the traced-count regime (post-scan counts; full-capacity
masked scatter-concat) are measured.

Self-provisions an 8-device virtual CPU mesh, so it must run in its own
process: ``python -m benchmarks.bench_sync``. Device counts are emulated on
host cores — ratios between the two typings are meaningful, absolute
milliseconds are not ICI numbers.
"""
import json
import time

N_DEV = 8
CAP = 125_000  # per-device samples -> 1M total


def measure() -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", N_DEV)
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu.utilities.buffers import CapacityBuffer
    from metrics_tpu.utilities.distributed import replicate_typed, sync_buffer_in_context

    mesh = Mesh(np.asarray(jax.devices()[:N_DEV]), ("dp",))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(N_DEV * CAP,)).astype(np.float32))

    def make(regime: str, typed: str):
        def prog(v):
            buf = CapacityBuffer(CAP, jnp.float32)
            buf.append(v)
            if regime == "traced":
                buf._host_count = None  # post-scan counts: full-capacity merge
            merged = sync_buffer_in_context(buf, "dp", typed=typed)
            val = merged.data.sum()  # zeros beyond the fill: plain sum is exact
            return replicate_typed(val, "dp") if typed == "varying" else val

        return jax.jit(jax.shard_map(prog, mesh=mesh, in_specs=P("dp"), out_specs=P()))

    out = {}
    expected = float(x.sum())
    for regime in ("static", "traced"):
        for typed in ("invariant", "varying"):
            fn = make(regime, typed)
            got = fn(x)
            got.block_until_ready()
            assert abs(float(got) - expected) < 1e-2 * max(1.0, abs(expected)), (float(got), expected)
            times = []
            for _ in range(7):
                t0 = time.perf_counter()
                fn(x).block_until_ready()
                times.append(time.perf_counter() - t0)
            times.sort()
            out[f"buffer_sync_1M_8dev_{regime}_{typed}"] = times[len(times) // 2] * 1000.0
    return out


def main() -> None:
    for name, ms in measure().items():
        print(json.dumps({"metric": name, "value": round(ms, 3), "unit": "ms"}))


if __name__ == "__main__":
    main()
