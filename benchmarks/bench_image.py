"""FID compute at 10k accumulated features (BASELINE.md config).

Times the aggregation path — streaming mean/cov from accumulated feature
sums and the eigh-based trace-sqrtm (the reference round-trips to
scipy.linalg.sqrtm on CPU, reference ``image/fid.py:60-94``; here it is a
single on-device XLA computation)."""
import json

import jax
import jax.numpy as jnp

from benchmarks._timing import measure_ms_scaled
from metrics_tpu.functional.image.fid import _compute_fid

N, D, K = 10_000, 2048, 10


def measure() -> dict:
    feats_r = jax.random.normal(jax.random.PRNGKey(0), (N, D)) * 0.5
    feats_f = jax.random.normal(jax.random.PRNGKey(1), (N, D)) * 0.55 + 0.05

    def fid_from_feats(fr, ff):
        mu1, mu2 = fr.mean(0), ff.mean(0)
        c1 = jnp.matmul((fr - mu1).T, fr - mu1, precision="float32") / (N - 1)
        c2 = jnp.matmul((ff - mu2).T, ff - mu2, precision="float32") / (N - 1)
        return _compute_fid(mu1, c1, mu2, c2)

    def make_run(k):
        @jax.jit
        def run(fr=feats_r, ff=feats_f):
            def body(i, acc):
                return acc + fid_from_feats(fr * (1.0 + 0.0001 * i), ff)
            return jax.lax.fori_loop(0, k, body, jnp.zeros(()))
        return run

    return {"fid_10k_2048d_compute": measure_ms_scaled(make_run, K)}


def measure_ssim(batch: int = 64, side: int = 256, k: int = 10) -> dict:
    """Batched SSIM forward (gaussian 11x11 window): the conv-heavy image
    kernel, mapped onto the MXU via XLA's grouped depthwise convolutions
    (the reference runs the same windows through eager torch F.conv2d,
    ``functional/image/ssim.py``)."""
    from metrics_tpu.functional import structural_similarity_index_measure

    preds = jax.random.uniform(jax.random.PRNGKey(0), (batch, 3, side, side), dtype=jnp.float32)
    target = jnp.clip(preds + 0.05 * jax.random.normal(jax.random.PRNGKey(1), preds.shape), 0, 1)

    def make_run(kk):
        @jax.jit
        def run(preds=preds, target=target):
            def body(i, acc):
                return acc + structural_similarity_index_measure(
                    jnp.clip(preds * (1.0 + 0.0001 * i), 0, 1), target
                )
            return jax.lax.fori_loop(0, kk, body, jnp.zeros(()))
        return run

    return {f"ssim_{batch}x3x{side}x{side}_compute": measure_ms_scaled(make_run, k)}


def main() -> None:
    for name, ms in {**measure(), **measure_ssim()}.items():
        print(json.dumps({"metric": name, "value": round(ms, 3), "unit": "ms"}))


if __name__ == "__main__":
    main()
