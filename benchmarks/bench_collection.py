"""MetricCollection Precision/Recall/F1 at 1M samples (BASELINE.md config).

Measures the jitted stat-scores accumulation the collection's compute
group shares (one update feeds P/R/F1), plus the torch-eager equivalent.
Prints one JSON line per configuration.
"""
import json

import jax
import jax.numpy as jnp

from benchmarks._timing import measure_ms_scaled
from metrics_tpu.functional.classification.stat_scores import _stat_scores_update

N, C, K = 1_000_000, 10, 5000  # the binary micro update is ~13 us; K must swamp dispatch RTT


def measure() -> dict:
    out = {}
    for mode, shape, make_target in (
        ("binary", (N,), lambda k: jax.random.randint(k, (N,), 0, 2)),
        ("multiclass", (N, C), lambda k: jax.random.randint(k, (N,), 0, C)),
    ):
        preds = jax.random.uniform(jax.random.PRNGKey(0), shape, dtype=jnp.float32)
        target = make_target(jax.random.PRNGKey(1))

        def make_run(k, preds=preds, target=target):
            @jax.jit
            def run(preds=preds, target=target):
                def body(i, acc):
                    p = preds + 0.0001 * i
                    tp, fp, tn, fn = _stat_scores_update(
                        p, target, reduce="micro", threshold=0.5, validate_args=False
                    )
                    return acc + tp
                return jax.lax.fori_loop(0, k, body, jnp.zeros((), jnp.int32))
            return run

        out[f"collection_statscores_{mode}_1M_update"] = measure_ms_scaled(make_run, K)
    return out


def main() -> None:
    for name, ms in measure().items():
        print(json.dumps({"metric": name, "value": round(ms, 3), "unit": "ms"}))


if __name__ == "__main__":
    main()
