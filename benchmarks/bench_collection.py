"""MetricCollection Precision/Recall/F1 at 1M samples (BASELINE.md config).

Measures the jitted stat-scores accumulation the collection's compute
group shares (one update feeds P/R/F1), plus the torch-eager equivalent.
Prints one JSON line per configuration.
"""
import json

import jax
import jax.numpy as jnp

from benchmarks._timing import measure_ms_scaled
from metrics_tpu.functional.classification.stat_scores import _stat_scores_update

N, C, K = 1_000_000, 10, 5000  # the binary micro update is ~13 us; K must swamp dispatch RTT


def measure() -> dict:
    out = {}
    for mode, shape, make_target in (
        ("binary", (N,), lambda k: jax.random.randint(k, (N,), 0, 2)),
        ("multiclass", (N, C), lambda k: jax.random.randint(k, (N,), 0, C)),
    ):
        preds = jax.random.uniform(jax.random.PRNGKey(0), shape, dtype=jnp.float32)
        target = make_target(jax.random.PRNGKey(1))

        def make_run(k, preds=preds, target=target):
            @jax.jit
            def run(preds=preds, target=target):
                def body(i, acc):
                    p = preds + 0.0001 * i
                    tp, fp, tn, fn = _stat_scores_update(
                        p, target, reduce="micro", threshold=0.5, validate_args=False
                    )
                    return acc + tp
                return jax.lax.fori_loop(0, k, body, jnp.zeros((), jnp.int32))
            return run

        out[f"collection_statscores_{mode}_1M_update"] = measure_ms_scaled(make_run, K)
    return out


def measure_compute_group_savings(n: int = 200_000, n_classes: int = 10, reps: int = 20) -> dict:
    """Eager class-API update cost: compute groups ON vs OFF.

    The reference's one quantitative perf claim is that compute groups give
    "2x-3x lower computational cost" on collections sharing state
    (docs overview, SURVEY.md §6). P/R/F1 all reduce to one stat-scores
    pass, so the grouped collection runs ONE update for all three.
    """
    import time

    from metrics_tpu import F1Score, MetricCollection, Precision, Recall

    preds = jax.random.uniform(jax.random.PRNGKey(0), (n, n_classes), dtype=jnp.float32)
    target = jax.random.randint(jax.random.PRNGKey(1), (n,), 0, n_classes)
    out = {}
    for label, grouped in (("on", True), ("off", False)):
        col = MetricCollection(
            {
                "precision": Precision(num_classes=n_classes, average="macro"),
                "recall": Recall(num_classes=n_classes, average="macro"),
                "f1": F1Score(num_classes=n_classes, average="macro"),
            },
            compute_groups=grouped,
        )
        col.update(preds, target)  # warm compile
        jax.block_until_ready(col["precision"].tp)
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            col.update(preds, target)
            jax.block_until_ready(col["precision"].tp)
            times.append(time.perf_counter() - t0)
        size = f"{n // 1000}k" if n >= 1000 else str(n)
        out[f"collection_prf1_{size}_update_groups_{label}"] = min(times) * 1000
    return out


def main() -> None:
    for name, ms in measure().items():
        print(json.dumps({"metric": name, "value": round(ms, 3), "unit": "ms"}))
    savings = measure_compute_group_savings()
    for name, ms in savings.items():
        print(json.dumps({"metric": name, "value": round(ms, 3), "unit": "ms"}))
    on = savings["collection_prf1_200k_update_groups_on"]
    off = savings["collection_prf1_200k_update_groups_off"]
    print(json.dumps({"metric": "collection_compute_group_savings", "value": round(off / on, 2), "unit": "x"}))


if __name__ == "__main__":
    main()
