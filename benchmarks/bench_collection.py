"""MetricCollection Precision/Recall/F1 at 1M samples (BASELINE.md config).

Measures the jitted stat-scores accumulation the collection's compute
group shares (one update feeds P/R/F1), plus the torch-eager equivalent.
Prints one JSON line per configuration.
"""
import json

import jax
import jax.numpy as jnp

from benchmarks._timing import measure_ms_scaled
from metrics_tpu.functional.classification.stat_scores import _stat_scores_update

N, C, K = 1_000_000, 10, 5000  # the binary micro update is ~13 us; K must swamp dispatch RTT


def measure() -> dict:
    out = {}
    for mode, shape, make_target in (
        ("binary", (N,), lambda k: jax.random.randint(k, (N,), 0, 2)),
        ("multiclass", (N, C), lambda k: jax.random.randint(k, (N,), 0, C)),
    ):
        preds = jax.random.uniform(jax.random.PRNGKey(0), shape, dtype=jnp.float32)
        target = make_target(jax.random.PRNGKey(1))

        def make_run(k, preds=preds, target=target):
            @jax.jit
            def run(preds=preds, target=target):
                def body(i, acc):
                    p = preds + 0.0001 * i
                    tp, fp, tn, fn = _stat_scores_update(
                        p, target, reduce="micro", threshold=0.5, validate_args=False
                    )
                    return acc + tp
                return jax.lax.fori_loop(0, k, body, jnp.zeros((), jnp.int32))
            return run

        out[f"collection_statscores_{mode}_1M_update"] = measure_ms_scaled(make_run, K)
    return out


def measure_compute_group_savings(n: int = 200_000, n_classes: int = 10, reps: int = 20) -> dict:
    """Eager class-API update cost: compute groups ON vs OFF.

    The reference's one quantitative perf claim is that compute groups give
    "2x-3x lower computational cost" on collections sharing state
    (docs overview, SURVEY.md §6). P/R/F1 all reduce to one stat-scores
    pass, so the grouped collection runs ONE update for all three.
    """
    import time

    from metrics_tpu import F1Score, MetricCollection, Precision, Recall

    preds = jax.random.uniform(jax.random.PRNGKey(0), (n, n_classes), dtype=jnp.float32)
    target = jax.random.randint(jax.random.PRNGKey(1), (n,), 0, n_classes)
    out = {}
    for label, grouped in (("on", True), ("off", False)):
        col = MetricCollection(
            {
                "precision": Precision(num_classes=n_classes, average="macro"),
                "recall": Recall(num_classes=n_classes, average="macro"),
                "f1": F1Score(num_classes=n_classes, average="macro"),
            },
            compute_groups=grouped,
        )
        col.update(preds, target)  # warm compile
        jax.block_until_ready(col["precision"].tp)
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            col.update(preds, target)
            jax.block_until_ready(col["precision"].tp)
            times.append(time.perf_counter() - t0)
        size = f"{n // 1000}k" if n >= 1000 else str(n)
        out[f"collection_prf1_{size}_update_groups_{label}"] = min(times) * 1000
    return out


def fusion_collection(n_classes: int = 10):
    """The acceptance config: 12 classification metrics over one prediction
    stream — stat-scores family (one compute group), confusion-matrix family
    (another), plus micro accuracy and hamming distance."""
    from metrics_tpu import (
        Accuracy,
        CohenKappa,
        ConfusionMatrix,
        F1Score,
        FBetaScore,
        HammingDistance,
        JaccardIndex,
        MatthewsCorrCoef,
        MetricCollection,
        Precision,
        Recall,
        Specificity,
        StatScores,
    )

    c = n_classes
    return MetricCollection(
        {
            "acc": Accuracy(num_classes=c),
            "prec": Precision(num_classes=c, average="macro"),
            "rec": Recall(num_classes=c, average="macro"),
            "f1": F1Score(num_classes=c, average="macro"),
            "spec": Specificity(num_classes=c, average="macro"),
            "stat": StatScores(num_classes=c, reduce="macro"),
            "fbeta": FBetaScore(num_classes=c, beta=2.0, average="macro"),
            "confmat": ConfusionMatrix(num_classes=c),
            "kappa": CohenKappa(num_classes=c),
            "mcc": MatthewsCorrCoef(num_classes=c),
            "jaccard": JaccardIndex(num_classes=c),
            "hamming": HammingDistance(),
        }
    )


def measure_collection_fusion(n: int = N, n_classes: int = C, n_batches: int = 16, reps: int = 8) -> dict:
    """Whole-collection fusion rows (round 7).

    - ``collection12_1M_epoch_wallclock`` — ONE fused
      ``make_collection_epoch`` launch folding a 16-batch 1M-sample epoch
      into all 12 metrics (update dedup: 4 update groups), plus the fused
      whole-collection compute launch. The donated carry re-threads, so
      calls are timed singly (the ``windowed_fold`` protocol).
    - ``collection12_launch_count`` — tracked epoch launches per fold,
      read from the obs ``epoch.launches`` counter family AFTER the timing
      pass (the layer stays off inside timed regions). Counted across ALL
      step labels (``obs.sum_counter``), so a fusion regression that falls
      back to one ``make_epoch`` per member reads 12x and fails the
      ``--compare`` gate; a broken routing that records NO launch raises
      here (the row must go missing loudly, never be fabricated).
    """
    import time

    from metrics_tpu import obs
    from metrics_tpu.steps import make_collection_epoch

    coll = fusion_collection(n_classes)
    batch = max(1, n // n_batches)
    preds = jax.random.uniform(jax.random.PRNGKey(0), (n_batches, batch, n_classes), dtype=jnp.float32)
    target = jax.random.randint(jax.random.PRNGKey(1), (n_batches, batch), 0, n_classes)
    preds.block_until_ready()

    init, epoch, compute = make_collection_epoch(coll)
    state, _ = epoch(init(), preds, target)  # warm: one trace+compile
    jax.block_until_ready(compute(state))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        state, _ = epoch(state, preds, target)
        jax.block_until_ready(compute(state))
        times.append(time.perf_counter() - t0)
    out = {"collection12_1M_epoch_wallclock": min(times) * 1000.0}

    # launch accounting outside the timed region: obs on, one fold, read
    # the whole epoch.launches label FAMILY, obs off again — per-member
    # fallback paths carry their own labels, and those must count
    was_enabled = obs.enabled()
    obs.enable()
    try:
        before = obs.sum_counter("epoch.launches")
        state, _ = epoch(state, preds, target)
        jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
        launches = obs.sum_counter("epoch.launches") - before
    finally:
        obs.enable(was_enabled)
    if launches <= 0:
        raise RuntimeError(
            "collection fusion launch accounting recorded ZERO epoch launches —"
            " the fused entry point is no longer routed through note_epoch_launch;"
            " refusing to fabricate the collection12_launch_count row"
        )
    out["collection12_launch_count"] = launches
    return out


def measure_collection_eager_epoch(n: int = N, n_classes: int = C, n_batches: int = 16, reps: int = 3) -> float:
    """The loop the fused epoch replaces: the eager class-API collection
    driven batch by batch (compute groups active, so this is the DEDUPED
    eager cost — the fusion win is on top of the 2-3x group saving), plus
    the per-member eager computes."""
    import time

    coll = fusion_collection(n_classes)
    batch = max(1, n // n_batches)
    preds = jax.random.uniform(jax.random.PRNGKey(0), (n_batches, batch, n_classes), dtype=jnp.float32)
    target = jax.random.randint(jax.random.PRNGKey(1), (n_batches, batch), 0, n_classes)
    preds.block_until_ready()

    def run_epoch():
        coll.reset()
        for i in range(n_batches):
            coll.update(preds[i], target[i])
        out = coll.compute()
        jax.block_until_ready(list(out.values()))

    run_epoch()  # warm compiles + group discovery
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run_epoch()
        times.append(time.perf_counter() - t0)
    return min(times) * 1000.0


def main() -> None:
    for name, ms in measure().items():
        print(json.dumps({"metric": name, "value": round(ms, 3), "unit": "ms"}))
    for name, value in measure_collection_fusion().items():
        unit = "launches" if name.endswith("launch_count") else "ms"
        print(json.dumps({"metric": name, "value": round(value, 3), "unit": unit}))
    savings = measure_compute_group_savings()
    for name, ms in savings.items():
        print(json.dumps({"metric": name, "value": round(ms, 3), "unit": "ms"}))
    on = savings["collection_prf1_200k_update_groups_on"]
    off = savings["collection_prf1_200k_update_groups_off"]
    print(json.dumps({"metric": "collection_compute_group_savings", "value": round(off / on, 2), "unit": "x"}))


if __name__ == "__main__":
    main()
