"""Root conftest: tolerant numeric comparison for docstring examples.

The reference runs every docstring example as a test with
``pytest-doctestplus``'s float comparison (``setup.cfg:1-13``). Here the
same effect comes from a custom ``doctest.OutputChecker``: if the expected
and actual outputs differ only in floating-point digits (platform drift —
TPU vs CPU matmul/reduction order, float32 repr length), they are compared
numerically with rtol=1e-3 instead of textually.

Doctests are run with ``python -m pytest --doctest-modules metrics_tpu``;
the regular suite under ``tests/`` is unaffected.
"""
import doctest
import re

from metrics_tpu.utilities.compile_cache import enable_persistent_cache

enable_persistent_cache()

_FLOAT_RE = re.compile(r"-?\d+\.\d*(?:[eE][+-]?\d+)?")


class _NumericOutputChecker(doctest.OutputChecker):
    def check_output(self, want: str, got: str, optionflags: int) -> bool:
        if super().check_output(want, got, optionflags):
            return True
        want_nums = _FLOAT_RE.findall(want)
        got_nums = _FLOAT_RE.findall(got)
        if not want_nums or len(want_nums) != len(got_nums):
            return False
        # the non-numeric skeleton must still match (whitespace-insensitive:
        # array reprs re-align padding when digit counts change)
        want_skel = re.sub(r"\s+", "", _FLOAT_RE.sub("{}", want))
        got_skel = re.sub(r"\s+", "", _FLOAT_RE.sub("{}", got))
        if want_skel != got_skel:
            return False
        for w, g in zip(want_nums, got_nums):
            w_f, g_f = float(w), float(g)
            if abs(w_f - g_f) > 1e-3 * max(1.0, abs(w_f)):
                return False
        return True


doctest.OutputChecker = _NumericOutputChecker
